"""TensorBoard event-file writer tests (utils/tb_events.py).

No TF/tensorboard package exists in this environment, so correctness is
checked against the wire format itself: events are decoded back with the
repo's own protobuf field iterator (data/example_proto.py) plus the TFRecord
reader with CRC verification on — the same checks TensorBoard's loader
performs when it tails a file.
"""

import os
import struct

import numpy as np
import pytest

from dcgan_tpu.data.example_proto import _iter_fields
from dcgan_tpu.data.tfrecord import read_tfrecords
from dcgan_tpu.utils.metrics import MetricWriter
from dcgan_tpu.utils.tb_events import TBEventWriter, png_dimensions


def decode_event(buf):
    """Event proto -> dict (wall_time, step, file_version, summary values)."""
    ev = {"values": []}
    for field, wt, payload in _iter_fields(buf):
        if field == 1:
            ev["wall_time"] = struct.unpack("<d", payload)[0]
        elif field == 2:
            ev["step"] = payload
        elif field == 3:
            ev["file_version"] = payload.decode()
        elif field == 5:
            for f2, w2, val in _iter_fields(payload):
                if f2 == 1:
                    ev["values"].append(decode_value(val))
    return ev


def decode_value(buf):
    out = {}
    for field, wt, payload in _iter_fields(buf):
        if field == 1:
            out["tag"] = payload.decode()
        elif field == 2:
            out["simple_value"] = struct.unpack("<f", payload)[0]
        elif field == 4:
            img = {}
            for f2, w2, p2 in _iter_fields(payload):
                if f2 == 1:
                    img["height"] = p2
                elif f2 == 2:
                    img["width"] = p2
                elif f2 == 4:
                    img["png"] = p2
            out["image"] = img
        elif field == 5:
            h = {}
            for f2, w2, p2 in _iter_fields(payload):
                if f2 in (1, 2, 3, 4, 5):
                    h[{1: "min", 2: "max", 3: "num", 4: "sum",
                       5: "sum_squares"}[f2]] = struct.unpack("<d", p2)[0]
                elif f2 == 6:
                    h["bucket_limit"] = list(
                        struct.unpack(f"<{len(p2) // 8}d", p2))
                elif f2 == 7:
                    h["bucket"] = list(struct.unpack(f"<{len(p2) // 8}d", p2))
            out["histo"] = h
    return out


def read_events(logdir):
    files = [f for f in os.listdir(logdir) if "tfevents" in f]
    assert len(files) == 1, files
    path = os.path.join(logdir, files[0])
    return [decode_event(rec)
            for rec in read_tfrecords(path, verify_crc=True)]


def test_version_header_and_scalar_roundtrip(tmp_path):
    w = TBEventWriter(str(tmp_path))
    w.add_scalar("loss/d_loss", 0.693, step=7)
    w.add_scalar("loss/g_loss", 1.25, step=7)
    w.close()
    events = read_events(str(tmp_path))
    assert events[0]["file_version"] == "brain.Event:2"
    assert events[1]["step"] == 7
    assert events[1]["values"][0]["tag"] == "loss/d_loss"
    np.testing.assert_allclose(events[1]["values"][0]["simple_value"], 0.693,
                               rtol=1e-6)
    np.testing.assert_allclose(events[2]["values"][0]["simple_value"], 1.25)
    assert events[1]["wall_time"] > 1e9  # sane unix time


def test_histogram_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    vals = rng.normal(size=1000)
    w = TBEventWriter(str(tmp_path))
    w.add_histogram_values("gen/h1", vals, step=3, bins=20)
    w.close()
    (_, ev) = read_events(str(tmp_path))
    h = ev["values"][0]["histo"]
    assert ev["values"][0]["tag"] == "gen/h1"
    assert len(h["bucket"]) == 20 and len(h["bucket_limit"]) == 20
    assert h["num"] == 1000
    np.testing.assert_allclose(h["sum"], vals.sum(), rtol=1e-6)
    np.testing.assert_allclose(h["sum_squares"], np.square(vals).sum(),
                               rtol=1e-6)
    np.testing.assert_allclose(h["min"], vals.min())
    np.testing.assert_allclose(h["max"], vals.max())
    assert sum(h["bucket"]) == 1000
    # right edges strictly increasing, last edge == max
    limits = h["bucket_limit"]
    assert all(b > a for a, b in zip(limits, limits[1:]))
    np.testing.assert_allclose(limits[-1], vals.max())


def test_histogram_bins_mismatch_rejected(tmp_path):
    w = TBEventWriter(str(tmp_path))
    with pytest.raises(ValueError, match="bin_edges"):
        w.add_histogram_bins("x", 0, bin_edges=[0, 1], bin_counts=[1, 2],
                             minimum=0, maximum=1, num=3, mean=0.5, std=0.1)
    w.close()


def test_image_event_roundtrip(tmp_path):
    from dcgan_tpu.utils.images import save_png

    img = np.linspace(0, 1, 16 * 24 * 3).reshape(16, 24, 3)
    png_path = str(tmp_path / "grid.png")
    save_png(png_path, img)
    png = open(png_path, "rb").read()
    assert png_dimensions(png) == (16, 24)

    logdir = str(tmp_path / "tb")
    w = TBEventWriter(logdir)
    w.add_image_png("samples", png, step=100)
    w.close()
    (_, ev) = read_events(logdir)
    v = ev["values"][0]
    assert v["tag"] == "samples"
    assert v["image"]["height"] == 16 and v["image"]["width"] == 24
    assert v["image"]["png"] == png


def test_metric_writer_mirrors_to_tensorboard(tmp_path):
    logdir = str(tmp_path)
    mw = MetricWriter(logdir, enabled=True, tensorboard=True)
    mw.write_scalars(1, {"d_loss": 0.5, "g_loss": 2.0})
    mw.write_histograms(1, {"gen/w": np.arange(10.0)})
    stats = {"gen/conv0": {
        "count": 8, "min": 0.0, "max": 1.0, "mean": 0.5, "std": 0.25,
        "zero_fraction": 0.125,
        "bin_counts": np.array([3, 5]), "bin_edges": np.array([0.0, 0.5, 1.0]),
    }}
    mw.write_activations(1, stats)
    mw.close()

    events = read_events(logdir)
    tags = [v["tag"] for e in events for v in e["values"]]
    assert "d_loss" in tags and "g_loss" in tags and "gen/w" in tags
    assert "gen/conv0/activations" in tags and "gen/conv0/sparsity" in tags
    act = next(v for e in events for v in e["values"]
               if v["tag"] == "gen/conv0/activations")
    assert act["histo"]["bucket"] == [3.0, 5.0]
    np.testing.assert_allclose(act["histo"]["sum"], 8 * 0.5)
    spars = next(v for e in events for v in e["values"]
                 if v["tag"] == "gen/conv0/sparsity")
    np.testing.assert_allclose(spars["simple_value"], 0.125)
    # JSONL channel still written alongside
    assert os.path.exists(os.path.join(logdir, "events.jsonl"))


def test_metric_writer_tensorboard_off(tmp_path):
    mw = MetricWriter(str(tmp_path), enabled=True, tensorboard=False)
    mw.write_scalars(1, {"d_loss": 0.5})
    mw.close()
    assert not [f for f in os.listdir(str(tmp_path)) if "tfevents" in f]


def test_cli_flag(tmp_path):
    from dcgan_tpu.train.cli import build_parser, config_from_args

    cfg = config_from_args(build_parser().parse_args([]))
    assert cfg.tensorboard
    cfg = config_from_args(build_parser().parse_args(["--no_tensorboard"]))
    assert not cfg.tensorboard
