"""Crash flight recorder + counter registry + observability-plane trainer
wiring (ISSUE 6): ring semantics, every dump trigger (watchdog / NaN abort
/ coordinated stop / uncaught exception, driven by FaultPlan), the
startup-partial satellite, and the defaults-parity A/B."""

import json
import os

import jax
import pytest

from dcgan_tpu.testing import chaos
from dcgan_tpu.train import coordination
from dcgan_tpu.train.flight_recorder import (
    FlightRecorder,
    read_dump,
    recorder_path,
)
from dcgan_tpu.utils.metrics import CounterRegistry, CounterSnapshot

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_chaos_state():
    chaos.reset()
    yield
    chaos.reset()


class TestRing:
    def test_capacity_bounds_and_order(self, tmp_path):
        fr = FlightRecorder(str(tmp_path / "d.jsonl"), capacity=3)
        for i in range(7):
            fr.record({"step": i})
        assert [r["step"] for r in fr.snapshot()] == [4, 5, 6]
        assert len(fr) == 3

    def test_zero_capacity_disables_everything(self, tmp_path):
        fr = FlightRecorder(str(tmp_path / "d.jsonl"), capacity=0)
        fr.record({"step": 1})
        assert not fr.enabled and len(fr) == 0
        assert fr.dump("exception") is None
        assert not os.path.exists(str(tmp_path / "d.jsonl"))

    def test_dump_roundtrip(self, tmp_path):
        path = str(tmp_path / "sub" / "d.jsonl")  # dir created on demand
        fr = FlightRecorder(path, capacity=4)
        for i in range(6):
            fr.record({"step": i, "gate": ""})
        out = fr.dump("nan-abort", step=5, extra={"error": "boom"})
        assert out == path and fr.dumps == 1
        header, records = read_dump(path)
        assert header["reason"] == "nan-abort" and header["step"] == 5
        assert header["error"] == "boom" and header["records"] == 4
        assert [r["step"] for r in records] == [2, 3, 4, 5]

    def test_context_supplied_and_fail_safe(self, tmp_path):
        calls = []

        def ctx():
            calls.append(1)
            if len(calls) == 1:
                return {"process": 7, "startup_partial": {"x_ms": 1.0}}
            raise RuntimeError("context exploded")

        fr = FlightRecorder(str(tmp_path / "d.jsonl"), capacity=2,
                            context=ctx)
        fr.dump("watchdog", step=3)
        header, _ = read_dump(str(tmp_path / "d.jsonl"))
        assert header["process"] == 7 and header["startup_partial"]
        # a raising context must not kill the crash path
        assert fr.dump("watchdog", step=4) is not None

    def test_last_dump_wins(self, tmp_path):
        fr = FlightRecorder(str(tmp_path / "d.jsonl"), capacity=2)
        fr.dump("coordinated-stop", step=1)
        fr.dump("exception", step=2)
        header, _ = read_dump(str(tmp_path / "d.jsonl"))
        assert header["reason"] == "exception" and fr.dumps == 2

    def test_read_dump_rejects_non_dumps(self, tmp_path):
        p = tmp_path / "x.jsonl"
        p.write_text('{"kind": "scalars"}\n')
        with pytest.raises(ValueError, match="not a flight-recorder"):
            read_dump(str(p))

    def test_recorder_path_is_per_process(self, monkeypatch):
        assert recorder_path("/ck").endswith("/ck/flight_recorder.jsonl")
        monkeypatch.setattr(jax, "process_index", lambda: 2)
        assert recorder_path("/ck").endswith("flight_recorder.p2.jsonl")


class TestCounterRegistry:
    def test_snapshot_pulls_registered_providers(self):
        reg = CounterRegistry()
        reg.provide("services_dropped", lambda: 3)
        reg.provide("rollbacks", lambda: 1)
        snap = reg.snapshot()
        assert snap.services_dropped == 3 and snap.rollbacks == 1
        assert snap.corrupt_records == 0  # unwired field stays default
        assert snap.as_dict()["services_dropped"] == 3

    def test_unknown_field_rejected(self):
        with pytest.raises(ValueError, match="unknown counter"):
            CounterRegistry().provide("nope", lambda: 0)
        with pytest.raises(ValueError, match="unknown counter"):
            CounterRegistry().provide_group(("rollbacks", "nope"),
                                            lambda: {})

    def test_group_provider_reads_source_once_per_snapshot(self):
        """provide_group exists so one counters() dict feeds several
        fields (CompileCacheMonitor): snapshot() must call it once, and
        extra keys in the returned mapping are ignored."""
        calls = []

        def src():
            calls.append(1)
            return {"compile_cache_requests": 5, "compile_cache_hits": 4,
                    "compile_cache_misses": 1, "saved_ms": 12.5}

        reg = CounterRegistry()
        reg.provide_group(("compile_cache_requests", "compile_cache_hits",
                           "compile_cache_misses"), src)
        snap = reg.snapshot()
        assert len(calls) == 1
        assert (snap.compile_cache_requests, snap.compile_cache_hits,
                snap.compile_cache_misses) == (5, 4, 1)

    def test_snapshot_is_frozen(self):
        snap = CounterSnapshot()
        with pytest.raises(Exception):
            snap.rollbacks = 5


class TestWatchdogDumpHook:
    def test_pre_dump_fires_before_on_trip(self):
        order = []
        wd = coordination.CollectiveWatchdog(
            0.1, poll_interval=0.02,
            pre_dump=lambda phase, step: order.append(("dump", phase, step)),
            on_trip=lambda phase, step: order.append(("trip", phase, step)))
        try:
            wd.arm("collective-save", 9)
            import time
            t0 = time.monotonic()
            while not order and time.monotonic() - t0 < 2.0:
                time.sleep(0.02)
            assert order[:2] == [("dump", "collective-save", 9),
                                 ("trip", "collective-save", 9)]
        finally:
            wd.close()

    def test_raising_pre_dump_does_not_block_the_trip(self):
        trips = []

        def bad_dump(phase, step):
            raise OSError("disk gone")

        wd = coordination.CollectiveWatchdog(
            0.1, poll_interval=0.02, pre_dump=bad_dump,
            on_trip=lambda phase, step: trips.append(step))
        try:
            wd.arm("final-save", 4)
            import time
            t0 = time.monotonic()
            while not trips and time.monotonic() - t0 < 2.0:
                time.sleep(0.02)
            assert trips == [4]
        finally:
            wd.close()

    def test_note_lands_in_null_watchdog_too(self):
        wd = coordination.make_watchdog(0.0)
        wd.set_note("slowest host: process 1")  # free no-op


def _tiny_cfg(tmp_path, **kw):
    from dcgan_tpu.config import ModelConfig, TrainConfig

    base = dict(
        model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                          compute_dtype="float32"),
        batch_size=16,
        checkpoint_dir=str(tmp_path / "ckpt"),
        sample_dir=str(tmp_path / "samples"),
        sample_every_steps=0, save_summaries_secs=0.0, save_model_secs=1e9,
        log_every_steps=0, tensorboard=False)
    base.update(kw)
    return TrainConfig(**base)


def _scalar_rows(root):
    rows = []
    with open(os.path.join(root, "ckpt", "events.jsonl")) as f:
        for line in f:
            e = json.loads(line)
            if e["kind"] == "scalars":
                rows.append((e["step"], e["values"]))
    return rows


@pytest.mark.slow
class TestTrainerDumpTriggers:
    """Each dying exit path of the real trainer ships the ring, driven by
    FaultPlan (the drill's subprocess half is tools/chaos_drill.py
    --only flight-recorder watchdog-dump, pinned in test_tools)."""

    def test_nan_abort_dump_last_record_is_failing_step(self, tmp_path):
        from dcgan_tpu.train.trainer import train

        chaos.set_plan(chaos.FaultPlan(nan_at_step=3))
        cfg = _tiny_cfg(tmp_path, nan_check_steps=1)
        with pytest.raises(FloatingPointError, match="step 3"):
            train(cfg, synthetic_data=True, max_steps=6)
        header, records = read_dump(
            os.path.join(cfg.checkpoint_dir, "flight_recorder.jsonl"))
        assert header["reason"] == "nan-abort" and header["step"] == 3
        assert records[-1]["step"] == 3 and records[-1]["gate"] == "trip"
        assert records[-1]["metrics"] and "d_loss" in records[-1]["metrics"]
        assert "counters" in records[-1]

    def test_coordinated_stop_dump(self, tmp_path):
        from dcgan_tpu.train.trainer import train

        chaos.set_plan(chaos.FaultPlan(sigterm_at_step=3))
        cfg = _tiny_cfg(tmp_path)
        state = train(cfg, synthetic_data=True, max_steps=6)
        assert int(jax.device_get(state["step"])) == 3  # stopped early
        header, records = read_dump(
            os.path.join(cfg.checkpoint_dir, "flight_recorder.jsonl"))
        assert header["reason"] == "coordinated-stop"
        assert header["step"] == 3 and header["signal"] > 0
        assert records and records[-1]["step"] <= 3

    def test_services_exception_dump(self, tmp_path):
        from dcgan_tpu.train.services import ServiceError
        from dcgan_tpu.train.trainer import train

        chaos.set_plan(chaos.FaultPlan(services_worker_crash=1))
        cfg = _tiny_cfg(tmp_path, save_summaries_secs=0.0,
                        log_every_steps=1)
        with pytest.raises(ServiceError):
            train(cfg, synthetic_data=True, max_steps=50)
        header, _ = read_dump(
            os.path.join(cfg.checkpoint_dir, "flight_recorder.jsonl"))
        assert header["reason"] == "exception"
        assert "ServiceError" in header["error"]

    def test_pre_first_step_death_carries_startup_partial(self, tmp_path):
        """The StartupProfile satellite: a run that dies before its first
        step dumps the phases completed so far instead of losing them."""
        from dcgan_tpu.train.trainer import train

        cfg = _tiny_cfg(tmp_path, data_dir=str(tmp_path / "empty"))
        # real-data mode with no shards on disk -> the loader raises
        # inside _train_run, after the init phase but before any step
        with pytest.raises(FileNotFoundError, match="no TFRecord shards"):
            train(cfg, synthetic_data=False, max_steps=4)
        header, records = read_dump(
            os.path.join(cfg.checkpoint_dir, "flight_recorder.jsonl"))
        assert header["reason"] == "exception" and records == []
        partial = header["startup_partial"]
        assert "perf/startup/init_ms" in partial
        assert "perf/startup/total_ms" not in partial  # never reached

    def test_flight_recorder_steps_zero_writes_nothing(self, tmp_path):
        from dcgan_tpu.train.trainer import train

        chaos.set_plan(chaos.FaultPlan(nan_at_step=2))
        cfg = _tiny_cfg(tmp_path, nan_check_steps=1,
                        flight_recorder_steps=0)
        with pytest.raises(FloatingPointError):
            train(cfg, synthetic_data=True, max_steps=4)
        assert not os.path.exists(
            os.path.join(cfg.checkpoint_dir, "flight_recorder.jsonl"))


@pytest.mark.slow
class TestFleetHealthEndToEnd:
    def test_fleet_metrics_at_cadence(self, tmp_path):
        """Single-process fleet plane: the same gather/derive path as
        multi-host (1-row table), fleet/* scalars at the cadence, skew 0."""
        from dcgan_tpu.train.trainer import train

        cfg = _tiny_cfg(tmp_path, fleet_health_steps=2,
                        save_summaries_secs=1e9, log_every_steps=1)
        train(cfg, synthetic_data=True, max_steps=5)
        fleet = {s: v for s, v in _scalar_rows(tmp_path)
                 if "fleet/step_ms_max" in v}
        assert set(fleet) == {2, 4}
        row = fleet[4]
        assert row["fleet/slowest_host"] == 0.0
        assert row["fleet/step_ms_skew"] == 0.0
        assert row["fleet/step_ms_max"] >= row["fleet/step_ms_min"] > 0.0
        assert row["fleet/dropped_total"] == 0.0


@pytest.mark.slow
class TestObservabilityParity:
    def test_defaults_vs_armed_jsonl_value_parity(self, tmp_path):
        """The acceptance parity criterion: the new observability knobs
        change what EXTRA telemetry exists, never the training values — a
        default run and a fully-armed run (fleet cadence on, recorder on,
        an untouched trigger file configured) carry identical scalar
        values outside the new fleet/* keys, and the default stream has
        none of the new keys at all."""
        from dcgan_tpu.train.trainer import train

        def run(root, **kw):
            train(_tiny_cfg(root, nan_check_steps=1, log_every_steps=1,
                            **kw), synthetic_data=True, max_steps=5)
            rows = {}
            for step, vals in _scalar_rows(root):
                # perf/ timing keys are wall-clock — excluded like every
                # prior parity test; fleet/ is the armed run's new family
                rows[step] = {k: v for k, v in vals.items()
                              if not k.startswith(("perf/", "fleet/"))}
            return rows

        a = run(tmp_path / "default")
        b = run(tmp_path / "armed",
                fleet_health_steps=1, flight_recorder_steps=16,
                profile_trigger=str(tmp_path / "trigger-never-touched"))
        assert a == b
        # and the default stream never carries the new key families
        for _, vals in _scalar_rows(tmp_path / "default"):
            assert not any(k.startswith(("fleet/", "perf/device/"))
                           for k in vals)
        # the armed-but-untouched trigger captured nothing
        for _, vals in _scalar_rows(tmp_path / "armed"):
            assert not any(k.startswith("perf/device/") for k in vals)
        # no crash -> no dump, even with the recorder armed
        assert not os.path.exists(
            str(tmp_path / "armed" / "ckpt" / "flight_recorder.jsonl"))
