"""Warm-start subsystem (ISSUE 5): persistent compile cache wiring, AOT
warmup shape set, single-pass verified restore, and warm-vs-cold parity.

What must hold: a second run against a primed cache dir records hits where
the cold dir recorded misses; the warmup plan covers every future call
shape (k=1 tail, steps_per_call scan, sampler/probe, the LR-backoff rebuild
variant) so a rollback drill triggers no recompile; default-flags event
streams stay byte-identical to warm-start-enabled ones (the parity
contract); and the fused restore reads each verified byte once, still
quarantining same-size corruption. The cross-process half of the story is
tools/bench_startup.py, pinned in test_tools.py."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcgan_tpu.testing import chaos
from dcgan_tpu.train import warmup

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _pristine_cache_state():
    """The persistent-cache config and the armed chaos plan are both
    process-global; neither may leak into later tests."""
    prev = {
        "jax_compilation_cache_dir": jax.config.jax_compilation_cache_dir,
        "jax_persistent_cache_min_compile_time_secs":
            jax.config.jax_persistent_cache_min_compile_time_secs,
        "jax_persistent_cache_min_entry_size_bytes":
            jax.config.jax_persistent_cache_min_entry_size_bytes,
    }
    chaos.reset()
    yield
    chaos.reset()
    for k, v in prev.items():
        jax.config.update(k, v)
    from jax._src import compilation_cache

    compilation_cache.reset_cache()


def _tiny_cfg(root, **kw):
    from dcgan_tpu.config import ModelConfig, TrainConfig

    base = dict(
        model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                          compute_dtype="float32"),
        batch_size=8,
        checkpoint_dir=os.path.join(str(root), "ckpt"),
        sample_dir=os.path.join(str(root), "samples"),
        sample_every_steps=0, save_summaries_secs=0.0, save_model_secs=1e9,
        log_every_steps=0, tensorboard=False, activation_summary_steps=0)
    base.update(kw)
    return TrainConfig(**base)


def _scalar_events(root):
    out = []
    with open(os.path.join(str(root), "ckpt", "events.jsonl")) as f:
        for line in f:
            e = json.loads(line)
            if e["kind"] == "scalars":
                out.append((e["step"], e["values"]))
    return out


def _startup_values(root):
    for _, vals in _scalar_events(root):
        if "perf/startup/total_ms" in vals:
            return vals
    return None


class TestCacheConfig:
    def test_resolve_prefers_flag_then_env(self):
        assert warmup.resolve_cache_dir("/a/b", {warmup.CACHE_ENV_VAR:
                                                 "/c"}) == "/a/b"
        assert warmup.resolve_cache_dir("", {warmup.CACHE_ENV_VAR: "/c"}) \
            == "/c"
        assert warmup.resolve_cache_dir("", {}) == ""

    def test_configure_points_jax_at_dir(self, tmp_path):
        d = str(tmp_path / "cc")
        assert warmup.configure_compile_cache("") is None
        assert warmup.configure_compile_cache(d) == d
        assert os.path.isdir(d)
        assert jax.config.jax_compilation_cache_dir == d
        # every program in this trainer is worth caching (DESIGN.md §6d)
        assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.0

    def test_configure_off_resets_a_previously_set_dir(self, tmp_path):
        """A second train() in the same process with the cache OFF must not
        keep running deserialized executables from the first run's dir —
        the donation-safety guards key on the cache being active, so a
        stale global config would disable them while the hazard persists."""
        from dcgan_tpu.utils.checkpoint import persistent_cache_active

        warmup.configure_compile_cache(str(tmp_path / "cc"))
        assert persistent_cache_active()
        assert warmup.configure_compile_cache("") is None
        assert not persistent_cache_active()

    def test_per_process_dirs_do_not_claim_fleet_warmth(self):
        """jaxlib <= 0.4.37 writes cache entries from the chief only, so
        per-process multi-host stores never fill on non-chief processes —
        warm proof (the watchdog arming shortcut) must not ride on them.
        Single-process is always servable."""
        assert warmup.cache_serves_all_processes(False)
        assert warmup.cache_serves_all_processes(True)  # 1 process

    def test_monitor_counts_and_unregisters(self, tmp_path):
        warmup.configure_compile_cache(str(tmp_path / "cc"))
        mon = warmup.CompileCacheMonitor()
        f = jax.jit(lambda x: x * 2 + 1)
        f(jnp.ones((8, 8))).block_until_ready()
        live = mon.counters()
        assert live["requests"] >= 1 and live["misses"] >= 1
        mon.close()
        baseline = mon.counters()
        g = jax.jit(lambda x: x * 3 - 1)
        g(jnp.ones((8, 8))).block_until_ready()
        assert mon.counters() == baseline  # closed monitors stop counting

    def test_backoff_config_matches_trainer_construction(self):
        from dcgan_tpu.config import TrainConfig

        cfg = TrainConfig(learning_rate=2e-4, d_learning_rate=1e-4)
        bk = warmup.backoff_config(cfg, 0.5)
        assert bk.learning_rate == pytest.approx(1e-4)
        assert bk.d_learning_rate == pytest.approx(5e-5)
        assert bk.g_learning_rate is None  # None stays None (lr fallback)


class TestWarmupPlan:
    def _pt_state(self, cfg):
        from dcgan_tpu.parallel import make_mesh, make_parallel_train

        mesh = make_mesh(cfg.mesh)
        pt = make_parallel_train(cfg, mesh)
        return mesh, pt, pt.init(jax.random.key(0))

    def test_plan_covers_known_future_call_shapes(self, tmp_path):
        """The full shape set: k=1 tail + steps_per_call scan + sampler +
        probe + summarize + the LR-backoff step variants, with a pre-built
        backoff ParallelTrain returned for the trainer to stash."""
        from dcgan_tpu.parallel import make_parallel_train

        cfg = _tiny_cfg(tmp_path, steps_per_call=2, sample_every_steps=2,
                        activation_summary_steps=2, nan_check_steps=2,
                        log_every_steps=2, nan_policy="rollback",
                        rollback_snapshot_steps=2, rollback_lr_backoff=0.5)
        mesh, pt, state = self._pt_state(cfg)
        z = jax.random.uniform(jax.random.key(1), (8, cfg.model.z_dim))
        plan, pt_backoff = warmup.build_warmup_plan(
            cfg, pt, state, sample_z=z, eval_z=z,
            make_backoff_pt=lambda c: make_parallel_train(c, mesh))
        names = [n for n, _, _ in plan]
        assert names == ["train_step", "state_copy", "multi_step@k2",
                         "sampler", "eval_losses", "summarize",
                         "train_step@lr_backoff",
                         "multi_step@k2@lr_backoff"]
        assert pt_backoff is not None
        assert pt_backoff.cfg.learning_rate == \
            pytest.approx(cfg.learning_rate * 0.5)

    def test_plan_minimal_when_probes_off(self, tmp_path):
        cfg = _tiny_cfg(tmp_path)
        _, pt, state = self._pt_state(cfg)
        plan, pt_backoff = warmup.build_warmup_plan(cfg, pt, state)
        assert [n for n, _, _ in plan] == ["train_step", "state_copy"]
        assert pt_backoff is None

    def test_aot_compile_times_every_program(self, tmp_path):
        cfg = _tiny_cfg(tmp_path)
        _, pt, state = self._pt_state(cfg)
        plan, _ = warmup.build_warmup_plan(cfg, pt, state)
        timings = warmup.aot_compile(plan)
        assert set(timings) == {"train_step", "state_copy"}
        assert all(ms > 0 for ms in timings.values())


@pytest.mark.slow
class TestCacheWiringEndToEnd:
    def test_cold_dir_misses_then_primed_dir_hits(self, tmp_path):
        """The tentpole's cache contract: a run against a cold cache dir
        records misses; a SECOND run (fresh jit objects, same programs,
        same dir) records hits and zero misses — the restart path
        deserializes instead of compiling."""
        from dcgan_tpu.train.trainer import train

        cache = str(tmp_path / "cache")
        cfg1 = _tiny_cfg(tmp_path / "r1", compile_cache_dir=cache,
                         aot_warmup=True)
        train(cfg1, synthetic_data=True, max_steps=3)
        cold = _startup_values(tmp_path / "r1")
        assert cold is not None
        assert cold["perf/compile_cache_misses"] > 0
        assert cold["perf/compile_ms/train_step"] > 0

        cfg2 = _tiny_cfg(tmp_path / "r2", compile_cache_dir=cache,
                         aot_warmup=True)
        train(cfg2, synthetic_data=True, max_steps=3)
        warm = _startup_values(tmp_path / "r2")
        assert warm is not None
        assert warm["perf/compile_cache_hits"] > 0
        assert warm["perf/compile_cache_misses"] == 0
        assert warm["perf/startup/warmup_ms"] > 0

    def test_rollback_drill_recompiles_nothing_warm(self, tmp_path, capsys):
        """The watchdog-adjacent warmup claim: with the backoff variant
        pre-compiled and the cache primed, a live NaN rollback with LR
        backoff swaps in the pre-warmed surface and the WHOLE drill —
        restore, replay, backoff dispatch — records zero cache misses."""
        from dcgan_tpu.train.trainer import train

        cache = str(tmp_path / "cache")
        kw = dict(compile_cache_dir=cache, aot_warmup=True,
                  nan_policy="rollback", nan_check_steps=1,
                  rollback_snapshot_steps=2, max_rollbacks=2,
                  rollback_lr_backoff=0.5)
        train(_tiny_cfg(tmp_path / "prime", **kw), synthetic_data=True,
              max_steps=3)  # no fault: primes every program incl. backoff

        mon = warmup.CompileCacheMonitor()
        before = mon.counters()
        chaos.set_plan(chaos.FaultPlan(nan_at_step=3))
        state = train(_tiny_cfg(tmp_path / "drill", **kw),
                      synthetic_data=True, max_steps=6)
        delta = mon.delta(mon.counters(), before)
        mon.close()
        assert int(jax.device_get(state["step"])) == 6
        out = capsys.readouterr().out
        assert "rolling back to last-good snapshot" in out
        assert "pre-warmed surface swapped in" in out
        assert delta["hits"] > 0
        assert delta["misses"] == 0, delta

    def test_warm_vs_cold_jsonl_value_parity(self, tmp_path):
        """The acceptance parity criterion: warm-start knobs change WHEN
        programs compile, never what they compute — scalar values per step
        identical modulo the perf/ channel, and the default run carries no
        warm-start keys at all."""
        from dcgan_tpu.train.trainer import train

        def run(root, **kw):
            train(_tiny_cfg(root, nan_check_steps=1, **kw),
                  synthetic_data=True, max_steps=5)
            rows = {}
            for step, vals in _scalar_events(root):
                rows[step] = {k: v for k, v in vals.items()
                              if not k.startswith("perf/")}
            return rows

        cold = run(tmp_path / "default")
        warm = run(tmp_path / "warm",
                   compile_cache_dir=str(tmp_path / "cache"),
                   aot_warmup=True)
        assert cold == warm
        # the default stream must not even carry the startup/cache keys
        for _, vals in _scalar_events(tmp_path / "default"):
            assert not any(k.startswith(("perf/startup/", "perf/compile"))
                           for k in vals)


class TestFusedRestore:
    def _ckpt(self, tmp_path):
        from dcgan_tpu.utils.checkpoint import Checkpointer

        return Checkpointer(str(tmp_path / "ck"), async_save=False)

    def _state(self, value):
        return {"w": jnp.full((64, 64), value, jnp.float32),
                "step": jnp.asarray(int(value), jnp.int32)}

    def test_same_size_corruption_quarantined(self, tmp_path, capsys):
        """Bit rot that preserves file SIZE sails past the stat pre-check
        and must be caught by the checksum pass running CONCURRENTLY with
        the Orbax read — the restored-from-bad-bytes tree is discarded and
        the previous step restores instead."""
        ck = self._ckpt(tmp_path)
        ck.save(1, self._state(1.0), force=True)
        ck.save(2, self._state(2.0), force=True)
        ck.wait()
        # flip one payload byte, size unchanged
        files = []
        for root, _, names in os.walk(os.path.join(ck.directory, "2")):
            files += [os.path.join(root, n) for n in names]
        target = max(files, key=os.path.getsize)
        with open(target, "r+b") as f:
            f.seek(os.path.getsize(target) // 2)
            b = f.read(1)
            f.seek(-1, os.SEEK_CUR)
            f.write(bytes([b[0] ^ 0xFF]))
        from dcgan_tpu.utils import checkpoint as ckpt_mod

        ckpt_mod._CRC_CACHE.clear()  # the flip is invisible to stat

        restored = ck.restore_latest(self._state(0.0))
        assert int(restored["step"]) == 1
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.full((64, 64), 1.0, np.float32))
        assert os.path.isdir(os.path.join(ck.directory, "2.corrupt"))
        assert "crc32 mismatch" in capsys.readouterr().out

    def test_restore_stats_read_once_and_hash_sharing(self, tmp_path):
        """Single-pass accounting: the verify layer reads each manifest
        byte at most once, and hashes computed at SAVE time (the manifest
        write) serve a same-process restore from the fingerprint cache
        without re-reading."""
        ck = self._ckpt(tmp_path)
        ck.save(1, self._state(1.0), force=True)
        ck.wait()  # manifest written -> hashes in the fingerprint cache
        with open(os.path.join(ck.directory, "integrity", "1.json")) as f:
            manifest_bytes = sum(rec["size"] for rec
                                 in json.load(f)["files"].values())

        restored = ck.restore_latest(self._state(0.0))
        assert int(restored["step"]) == 1
        stats = ck.last_restore_stats
        assert stats is not None
        assert stats["files"] > 0
        assert stats["bytes_read"] + stats["bytes_cached"] == manifest_bytes
        # same process, same bytes: the save-time hashes did the work
        assert stats["bytes_cached"] == manifest_bytes
        assert stats["restore_ms"] > 0

    def test_fused_large_file_path_verifies_and_quarantines(self, tmp_path,
                                                            monkeypatch):
        """With the structural-first threshold forced to 0 every file takes
        the FUSED path (background CRC concurrent with the Orbax read):
        a clean step restores with correct read-once stats, and same-size
        corruption still discards the concurrently-restored tree and falls
        back."""
        from dcgan_tpu.utils import checkpoint as ckpt_mod

        monkeypatch.setattr(ckpt_mod, "_PREPARSE_VERIFY_MAX_BYTES", 0)
        ck = self._ckpt(tmp_path)
        ck.save(1, self._state(1.0), force=True)
        ck.save(2, self._state(2.0), force=True)
        ck.wait()
        with open(os.path.join(ck.directory, "integrity", "2.json")) as f:
            manifest_bytes = sum(rec["size"] for rec
                                 in json.load(f)["files"].values())
        restored = ck.restore_latest(self._state(0.0))
        assert int(restored["step"]) == 2
        stats = ck.last_restore_stats
        assert stats["bytes_read"] + stats["bytes_cached"] == manifest_bytes

        # now corrupt step 2 in place (same size) — the fused path must
        # discard the concurrent restore and fall back to step 1
        files = []
        for root, _, names in os.walk(os.path.join(ck.directory, "2")):
            files += [os.path.join(root, n) for n in names]
        target = max(files, key=os.path.getsize)
        with open(target, "r+b") as f:
            f.seek(os.path.getsize(target) // 2)
            b = f.read(1)
            f.seek(-1, os.SEEK_CUR)
            f.write(bytes([b[0] ^ 0xFF]))
        ckpt_mod._CRC_CACHE.clear()
        restored = ck.restore_latest(self._state(0.0))
        assert int(restored["step"]) == 1
        assert os.path.isdir(os.path.join(ck.directory, "2.corrupt"))

    def test_transient_stat_error_does_not_condemn(self, tmp_path,
                                                   monkeypatch):
        """PR 4's retry contract extended to the new stat pre-screen: one
        transient EIO on a stat must get its bounded retries instead of
        permanently quarantining an intact checkpoint."""
        ck = self._ckpt(tmp_path)
        ck.save(1, self._state(1.0), force=True)
        ck.wait()
        real_stat = os.stat
        tripped = {}

        def flaky_stat(path, *a, **kw):
            p = os.fspath(path)
            if "integrity" not in p and str(ck.directory) in p \
                    and p.endswith("_METADATA") and "once" not in tripped:
                tripped["once"] = True
                raise OSError(5, "Input/output error", p)
            return real_stat(path, *a, **kw)

        monkeypatch.setattr(os, "stat", flaky_stat)
        assert ck._verify_step(1) == (True, "verified")
        assert tripped  # the fault actually fired
        assert not os.path.isdir(os.path.join(ck.directory, "1.corrupt"))

    def test_verify_step_contract_unchanged(self, tmp_path):
        ck = self._ckpt(tmp_path)
        ck.save(3, self._state(3.0), force=True)
        ck.wait()
        assert ck._verify_step(3) == (True, "verified")

    def test_rebase_when_cache_active_preserves_values(self, tmp_path):
        """With the persistent cache configured, restored trees are
        rebased onto XLA-owned buffers (the donation-safety workaround) —
        values and shardings unchanged."""
        warmup.configure_compile_cache(str(tmp_path / "cc"))
        ck = self._ckpt(tmp_path)
        ck.save(1, self._state(5.0), force=True)
        ck.wait()
        restored = ck.restore_latest(self._state(0.0))
        assert int(restored["step"]) == 5
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.full((64, 64), 5.0, np.float32))


class TestStartupProfile:
    def test_phases_accumulate_and_first_step_wins_once(self):
        from dcgan_tpu.utils.profiling import StartupProfile

        sp = StartupProfile()
        with sp.phase("init"):
            pass
        with sp.phase("init"):
            pass
        assert not sp.done
        sp.first_step()
        total = sp.summary()["perf/startup/total_ms"]
        sp.first_step()  # idempotent
        assert sp.summary()["perf/startup/total_ms"] == total
        assert sp.summary()["perf/startup/init_ms"] >= 0
