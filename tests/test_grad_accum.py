"""Gradient accumulation (TrainConfig.grad_accum): K scanned microbatches
per optimizer update — beyond-reference large-batch emulation (the reference
always applies per-batch updates, image_train.py:156-158).

What must hold:
- the accumulated step is a drop-in train_step (state tree, metrics, step
  count all unchanged in shape),
- it composes with both parallel backends (the sharded program equals the
  single-device program on the same global batch),
- config validation rejects the undefined combinations.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
from dcgan_tpu.parallel import make_parallel_train
from dcgan_tpu.train import make_train_step

TINY = ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                   compute_dtype="float32")


def real_batch(n=16, size=16):
    rng = np.random.default_rng(0)
    return jnp.asarray(
        np.tanh(rng.normal(size=(n, size, size, 3))).astype(np.float32))


def tree_max_abs(t):
    return max(float(jnp.max(jnp.abs(x)))
               for x in jax.tree_util.tree_leaves(t))


def test_accum_step_runs_and_updates():
    """K=4 on batch 16: one step, finite metrics, params moved, EMA/step
    bookkeeping identical to the K=1 path's contract."""
    cfg = TrainConfig(model=TINY, batch_size=16, grad_accum=4,
                      g_ema_decay=0.9)
    fns = make_train_step(cfg)
    s0 = fns.init(jax.random.key(0))
    s1, m = jax.jit(fns.train_step)(s0, real_batch(), jax.random.key(1))
    assert int(s1["step"]) == 1
    for k, v in m.items():
        assert np.isfinite(float(v)), (k, v)
    # both nets actually updated
    d0 = jax.tree_util.tree_map(lambda a, b: a - b,
                                s0["params"], s1["params"])
    assert tree_max_abs(d0["gen"]) > 0 and tree_max_abs(d0["disc"]) > 0
    # EMA tracked the new generator weights with decay 0.9
    want = jax.tree_util.tree_map(
        lambda e, p: 0.9 * e + 0.1 * p, s0["ema_gen"], s1["params"]["gen"])
    np.testing.assert_allclose(
        tree_max_abs(jax.tree_util.tree_map(lambda a, b: a - b,
                                            want, s1["ema_gen"])), 0,
        atol=1e-6)


@pytest.mark.slow
def test_accum_close_to_full_batch_step():
    """Same batch, same key: K=2 vs K=1 may differ only through
    per-microbatch BN moments — losses must land in the same neighborhood
    (this is a sanity band, not an exactness claim; exact equality is not
    the accumulation contract under batch-stat BN)."""
    xs, key = real_batch(), jax.random.key(3)
    base = TrainConfig(model=TINY, batch_size=16)
    f1 = make_train_step(base)
    _, m1 = jax.jit(f1.train_step)(f1.init(jax.random.key(0)), xs, key)
    f2 = make_train_step(dataclasses.replace(base, grad_accum=2))
    _, m2 = jax.jit(f2.train_step)(f2.init(jax.random.key(0)), xs, key)
    for k in m1:
        assert abs(float(m1[k]) - float(m2[k])) < 0.5, (
            k, float(m1[k]), float(m2[k]))


@pytest.mark.slow
def test_accum_exact_without_bn():
    """With a BN-free family (stylegan: empty state tree, nothing couples
    samples) K=2 must reproduce K=1 EXACTLY — mean of per-microbatch mean
    gradients equals the full-batch mean, so the whole post-step state
    matches to float32 accumulation-order tolerance.

    Comparing ONLY params would be toothless here: Adam's update is
    scale-invariant (m̂/√v̂), so a sum-vs-mean bug (grads K× too big) moves
    one step's params only at eps scale. It is the OPTIMIZER MOMENTS that
    scream — m off by K, v by K² — so the assertion walks params AND both
    Adam chains (ADVICE r3 #1: the BN sanity band above cannot pin this)."""
    tiny_sg = ModelConfig(arch="stylegan", output_size=16, gf_dim=8,
                          df_dim=8, compute_dtype="float32")
    xs, key = real_batch(), jax.random.key(3)
    base = TrainConfig(model=tiny_sg, batch_size=16)
    f1 = make_train_step(base)
    s1, _ = jax.jit(f1.train_step)(f1.init(jax.random.key(0)), xs, key)
    f2 = make_train_step(dataclasses.replace(base, grad_accum=2))
    s2, _ = jax.jit(f2.train_step)(f2.init(jax.random.key(0)), xs, key)
    for part in ("params", "opt", "ema_gen"):
        flat1 = jax.tree_util.tree_leaves_with_path(s1[part])
        flat2 = jax.tree_util.tree_leaves(s2[part])
        assert len(flat1) == len(flat2)
        for (path, a), b in zip(flat1, flat2):
            np.testing.assert_allclose(
                np.asarray(a, dtype=np.float64),
                np.asarray(b, dtype=np.float64),
                rtol=1e-3, atol=5e-8,
                err_msg=f"{part}{jax.tree_util.keystr(path)}")


@pytest.mark.slow
@pytest.mark.parametrize(
    "mesh_cfg",
    [pytest.param(MeshConfig(), id="dp8"),
     pytest.param(MeshConfig(model=2), id="dp4xtp2")])
def test_sharded_accum_matches_single_device(mesh_cfg):
    """The sharded accumulation program must equal the unsharded one on the
    same global batch — the same equivalence contract as
    test_parallel.py::test_sharded_step_matches_single_device, now with the
    (K, micro, ...) reshapes pinned by constrain_micro."""
    cfg = TrainConfig(model=TINY, batch_size=16, grad_accum=2,
                      mesh=mesh_cfg)
    xs, key = real_batch(), jax.random.key(3)

    fns = make_train_step(cfg)
    s_ref, m_ref = jax.jit(fns.train_step)(fns.init(jax.random.key(0)), xs,
                                           key)

    pt = make_parallel_train(cfg)
    s_par, m_par = pt.step(pt.init(jax.random.key(0)), xs, key)

    np.testing.assert_allclose(float(m_par["d_loss"]),
                               float(m_ref["d_loss"]), rtol=1e-5)
    np.testing.assert_allclose(float(m_par["g_loss"]),
                               float(m_ref["g_loss"]), rtol=1e-5)
    diff = jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))),
        s_ref["params"], jax.device_get(s_par["params"]))
    assert max(jax.tree_util.tree_leaves(diff)) \
        <= 2 * cfg.learning_rate + 1e-5


@pytest.mark.slow
def test_shard_map_accum_runs():
    """Accumulation inside shard_map: the reshape is per-device local, so
    the local batch (16/8 = 2) must split into K=2 microbatches of 1."""
    cfg = TrainConfig(model=TINY, batch_size=16, grad_accum=2,
                      backend="shard_map")
    pt = make_parallel_train(cfg)
    s, m = pt.step(pt.init(jax.random.key(0)), real_batch(),
                   jax.random.key(1))
    assert int(s["step"]) == 1
    for k, v in m.items():
        assert np.isfinite(float(v)), (k, v)


@pytest.mark.slow
def test_accum_with_n_critic():
    """n_critic > 1 x grad_accum > 1: each scanned critic iteration applies
    one Adam update from its own K-microbatch accumulation (the WGAN-GP
    memory-bound composition). One step must run, report finite metrics
    including the gradient penalty, and advance the critic's schedule by
    n_critic updates (opt state count)."""
    cfg = TrainConfig(model=TINY, batch_size=16, grad_accum=2,
                      n_critic=2, loss="wgan-gp")
    fns = make_train_step(cfg)
    s1, m = jax.jit(fns.train_step)(fns.init(jax.random.key(0)),
                                    real_batch(), jax.random.key(1))
    assert int(s1["step"]) == 1
    for k, v in m.items():
        assert np.isfinite(float(v)), (k, v)
    assert "gp" in m
    # the critic's Adam chain counted n_critic updates in this one step
    counts = [int(v) for path, v in
              jax.tree_util.tree_leaves_with_path(s1["opt"]["disc"])
              if any(getattr(p, "name", "") == "count" for p in path)]
    assert counts and all(c == cfg.n_critic for c in counts), counts


@pytest.mark.slow
@pytest.mark.parametrize("accum", [1, 2])
def test_shard_map_critic_loop(accum):
    """shard_map + n_critic>1: the critic-scan metric carry must be
    data-axis-varying (steps.py::_zero_metric) or the scan rejects the
    carry types at trace time — a latent defect for accum=1 too, exposed
    when grad_accum composition made the path reachable."""
    cfg = TrainConfig(model=TINY, batch_size=16, grad_accum=accum,
                      n_critic=2, loss="wgan-gp", backend="shard_map")
    pt = make_parallel_train(cfg)
    s, m = pt.step(pt.init(jax.random.key(0)), real_batch(),
                   jax.random.key(1))
    assert int(s["step"]) == 1
    for k, v in m.items():
        assert np.isfinite(float(v)), (k, v)


def test_validation():
    with pytest.raises(ValueError, match="grad_accum must be >= 1"):
        TrainConfig(model=TINY, grad_accum=0)
    with pytest.raises(ValueError, match="multiple of"):
        TrainConfig(model=TINY, batch_size=16, grad_accum=3)
    # shard_map: microbatch must divide over the data shards
    bad = TrainConfig(model=TINY, batch_size=16, grad_accum=4,
                      backend="shard_map")
    with pytest.raises(ValueError, match="microbatch"):
        make_parallel_train(bad)
    # gspmd: same guard (silent GSPMD padding rejected)
    bad2 = TrainConfig(model=TINY, batch_size=16, grad_accum=4)
    with pytest.raises(ValueError, match="microbatch"):
        make_parallel_train(bad2)
