"""tools/convert_torch_embedder.py: the exported npz must reproduce the torch
tower's forward under make_npz_feature_fn (VERDICT r1 #2 — the conversion
path onto the feature schema, proven against torch itself)."""

import sys

import numpy as np
import pytest

sys.path.insert(0, "tools")

torch = pytest.importorskip("torch")

from convert_torch_embedder import (  # noqa: E402
    _fold_bn,
    convert_state_dict,
    main,
)
from dcgan_tpu.evals.features import make_npz_feature_fn  # noqa: E402


def _torch_tower():
    """Stride-2 LeakyReLU(0.2) tower — the exact architecture the npz
    harness runs (features.py::_build_conv_stack)."""
    torch.manual_seed(0)
    return torch.nn.Sequential(
        torch.nn.Conv2d(3, 8, 5, stride=2, padding=2),
        torch.nn.LeakyReLU(0.2),
        torch.nn.Conv2d(8, 16, 5, stride=2, padding=2),
        torch.nn.LeakyReLU(0.2),
    )


def _same_pad(n: int, stride: int, kernel: int):
    """XLA SAME padding (asymmetric, favors the high side) — the harness's
    conv semantics. torch's symmetric `padding=k//2` differs for stride 2,
    so the torch reference must pad explicitly to compare."""
    out = -(-n // stride)
    total = max(0, (out - 1) * stride + kernel - n)
    return total // 2, total - total // 2


def _torch_features(tower, x_nhwc, proj):
    with torch.no_grad():
        h = torch.from_numpy(np.transpose(x_nhwc, (0, 3, 1, 2)))
        pooled = []
        for layer in tower:
            if isinstance(layer, torch.nn.Conv2d):
                k = layer.kernel_size[0]
                s = layer.stride[0]
                lo_h, hi_h = _same_pad(h.shape[2], s, k)
                lo_w, hi_w = _same_pad(h.shape[3], s, k)
                h = torch.nn.functional.pad(h, (lo_w, hi_w, lo_h, hi_h))
                h = torch.nn.functional.conv2d(h, layer.weight, layer.bias,
                                               stride=s, padding=0)
                # harness applies lrelu THEN pools; replicate exactly
                pooled.append(
                    torch.nn.functional.leaky_relu(h, 0.2).mean(dim=(2, 3)))
            else:
                h = layer(h)
        feats = torch.cat(pooled, dim=1).numpy()
    return feats @ proj


class TestConvertStateDict:
    def test_forward_parity_with_torch(self, tmp_path):
        tower = _torch_tower()
        arrays = convert_state_dict(tower.state_dict(), proj_dim=32, seed=1)
        path = str(tmp_path / "f.npz")
        np.savez(path, **arrays)

        feature_fn, dim = make_npz_feature_fn(path)
        assert dim == 32

        x = np.random.default_rng(0).uniform(
            -1, 1, size=(4, 16, 16, 3)).astype(np.float32)
        ours = np.asarray(feature_fn(x))
        theirs = _torch_features(tower, x, arrays["proj"])
        np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-5)

    def test_3x3_kernel_parity(self, tmp_path):
        """Parity holds across kernel sizes, not just the 5x5 default —
        3x3 exercises a different SAME pad split (0,1 at stride 2)."""
        torch.manual_seed(1)
        tower = torch.nn.Sequential(
            torch.nn.Conv2d(3, 8, 3, stride=2, padding=1))
        arrays = convert_state_dict(tower.state_dict(), proj_dim=8, seed=2)
        path = str(tmp_path / "f3.npz")
        np.savez(path, **arrays)
        feature_fn, _ = make_npz_feature_fn(path)
        x = np.random.default_rng(1).uniform(
            -1, 1, size=(2, 8, 8, 3)).astype(np.float32)
        ours = np.asarray(feature_fn(x))
        theirs = _torch_features(tower, x, arrays["proj"])
        np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-5)

    def test_no_conv_weights_rejected(self):
        with pytest.raises(ValueError, match="no rank-4"):
            convert_state_dict({"fc.weight": torch.zeros(4, 4)}, 8)

    def test_bn_fold_closed_form(self):
        w = np.ones((2, 1, 1, 1), np.float32)
        wf, bf = _fold_bn(w, np.asarray([2.0, 2.0], np.float32),
                          np.asarray([1.0, 1.0], np.float32),
                          np.asarray([0.5, 0.5], np.float32),
                          np.asarray([4.0, 4.0], np.float32), eps=0.0)
        np.testing.assert_allclose(wf[:, 0, 0, 0], [1.0, 1.0])
        np.testing.assert_allclose(bf, [0.5, 0.5])

    def test_cli_end_to_end(self, tmp_path):
        tower = _torch_tower()
        sd_path = str(tmp_path / "tower.pt")
        torch.save(tower.state_dict(), sd_path)
        out = str(tmp_path / "out.npz")
        main(["--state_dict", sd_path, "--proj_dim", "16", "--out", out])
        feature_fn, dim = make_npz_feature_fn(out)
        assert dim == 16
        x = np.zeros((1, 16, 16, 3), np.float32)
        assert np.asarray(feature_fn(x)).shape == (1, 16)
