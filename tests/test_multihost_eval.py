"""Distributed FID/KID scoring: two real OS processes split the sample
budget, stream independent real/fake shards, and all-gather the moment
statistics + KID reservoirs into one global score (evals/job.py
allgather_merge_*). The reference had no eval at all (SURVEY.md §4)."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # see pytest.ini: excluded from the smoke tier

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_WORKER_CODE = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)
jax.distributed.initialize(coordinator_address=os.environ["MH_COORD"],
                           num_processes=2,
                           process_id=int(os.environ["MH_PID"]))
from dcgan_tpu.evals.__main__ import main
main(["--checkpoint_dir", os.environ["MH_CKPT"], "--synthetic",
      "--multihost", "--kid", "--num_samples", "256", "--batch_size", "32",
      "--kid_pool", "128", "--kid_subset_size", "64", "--kid_subsets", "8"])
print(f"EVAL_OK pid={jax.process_index()}", flush=True)
"""


class TestDistributedScoring:
    def test_two_process_eval_matches_contract(self, tmp_path):
        from dcgan_tpu.config import ModelConfig, TrainConfig
        from dcgan_tpu.train.trainer import train

        ckpt_dir = str(tmp_path / "ckpt")
        train(TrainConfig(
            model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                              compute_dtype="float32"),
            batch_size=8, checkpoint_dir=ckpt_dir,
            sample_dir=str(tmp_path / "sm"), sample_every_steps=0,
            save_summaries_secs=1e9, save_model_secs=1e9,
            log_every_steps=0), synthetic_data=True, max_steps=1)

        port = _free_port()
        procs = []
        for pid in range(2):
            env = dict(os.environ)
            env.pop("JAX_COORDINATOR_ADDRESS", None)
            env.update({"MH_COORD": f"127.0.0.1:{port}",
                        "MH_PID": str(pid), "MH_CKPT": ckpt_dir,
                        "PYTHONPATH": _REPO})
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _WORKER_CODE], env=env, cwd=_REPO,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=560)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for p, out in zip(procs, outs):
            assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"

        # chief printed the one JSON line; the other process printed none
        json_lines = [l for l in outs[0].splitlines() if l.startswith("{")]
        assert len(json_lines) == 1, outs[0][-2000:]
        result = json.loads(json_lines[0])
        assert result["num_samples"] == 256          # the GLOBAL budget
        assert np.isfinite(result["fid"]) and result["fid"] > 0
        assert np.isfinite(result["kid"])
        assert result["step"] == 1
        assert not [l for l in outs[1].splitlines() if l.startswith("{")]
        assert "EVAL_OK pid=1" in outs[1]


class TestMergeHelpers:
    def test_allgather_passthrough_single_process(self):
        from dcgan_tpu.evals.fid import StreamingStats
        from dcgan_tpu.evals.job import allgather_merge_stats
        from dcgan_tpu.evals.kid import FeaturePool
        from dcgan_tpu.evals.job import allgather_merge_pool

        stats = StreamingStats(4)
        stats.update(np.ones((8, 4), np.float32))
        assert allgather_merge_stats(stats) is stats

        pool = FeaturePool(4, 8)
        pool.update(np.ones((8, 4), np.float32))
        assert allgather_merge_pool(pool) is pool

    def test_pool_from_features_round_trip(self):
        from dcgan_tpu.evals.job import pool_from_features

        feats = np.arange(12, dtype=np.float32).reshape(4, 3)
        pool = pool_from_features(feats, n_seen=20, capacity=4)
        np.testing.assert_array_equal(pool.features(), feats)
        assert pool.n_seen == 20

    def test_uneven_budget_rejected(self, monkeypatch):
        """distributed num_samples must divide over processes — the guard
        that keeps the gathered pool buffers equal-shaped."""
        import jax

        from dcgan_tpu.evals.job import compute_fid

        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        with pytest.raises(ValueError, match="divide evenly"):
            compute_fid(lambda z: z, iter(()), image_size=8, num_samples=7,
                        batch_size=4, distributed=True)

    def test_f64_gather_preserves_bits(self):
        """_allgather_f64 must round-trip exact float64 bit patterns
        (plain process_allgather canonicalizes f64 -> f32)."""
        from dcgan_tpu.evals.job import _allgather_f64

        x = np.asarray([1.0 + 2 ** -40, np.pi, 1e300], np.float64)
        out = _allgather_f64(x)  # single-process: leading axis of 1
        np.testing.assert_array_equal(out.reshape(-1), x)
        assert out.dtype == np.float64

    def test_split_budget_validated(self):
        """distributed num_samples must divide over processes; on one
        process any value divides, so drive the error via the helper's
        contract directly."""
        from dcgan_tpu.evals.job import compute_fid

        # single-process distributed=True is legal (n_proc=1) — smoke that
        # the path works end to end with a trivial sampler
        import jax.numpy as jnp

        def sample_fn(z):
            return jnp.zeros((z.shape[0], 8, 8, 3), jnp.float32)

        def data():
            rng = np.random.default_rng(0)
            while True:
                yield jnp.asarray(rng.uniform(-1, 1, (32, 8, 8, 3)),
                                  jnp.float32)

        out = compute_fid(sample_fn, data(), image_size=8, num_samples=64,
                          batch_size=32, distributed=True)
        assert np.isfinite(out["fid"]) and out["fid"] > 0
