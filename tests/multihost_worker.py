"""Subprocess body for the two-process multi-host test (test_multihost.py).

Each process owns 4 virtual CPU devices; jax.distributed.initialize forms the
2-process job over localhost gRPC — the DCN path that replaces the reference's
ClusterSpec/Server bring-up (image_train.py:52-63). Runs the real trainer
(synthetic data) for a few steps: sharded SPMD step over the 8-device global
mesh, chief-gated metrics + sample grid, collective final checkpoint.
"""

import os
import sys

_LOCAL = int(os.environ.get("MH_LOCAL_DEVICES", "4"))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={_LOCAL}")

import jax  # noqa: E402

from dcgan_tpu.testing.multihost import configure_cpu_multiprocess  # noqa: E402

configure_cpu_multiprocess(jax)


def main() -> None:
    coord = os.environ["MH_COORD"]
    nproc = int(os.environ["MH_NPROC"])
    pid = int(os.environ["MH_PID"])
    workdir = os.environ["MH_DIR"]
    backend = os.environ.get("MH_BACKEND", "gspmd")

    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc, jax.process_count()
    assert jax.local_device_count() == _LOCAL, jax.local_device_count()
    assert jax.device_count() == _LOCAL * nproc, jax.device_count()

    from dcgan_tpu.config import ModelConfig, TrainConfig
    from dcgan_tpu.train.trainer import train

    fid = os.environ.get("MH_FID") == "1"
    # MH_SPC > 1: the scanned multi-step dispatch (steps_per_call) under a
    # real 2-process job — cadences must be multiples of the call size
    spc = int(os.environ.get("MH_SPC", "1"))
    # MH_SPATIAL=N (N>1): the distributed long-context path — image height
    # sharded over an N-way "model" axis with ring attention (ppermute k/v
    # around the sequence axis) running under the SAME jax.distributed job
    # that carries the data-parallel gradient psums over localhost DCN.
    # N > 2 makes the ring MULTI-hop: with the model axis laid out across
    # processes, at least one k/v rotation (and, under MH_PALLAS, one
    # homeward (dk, dv) rotation of the flash backward) crosses a real
    # process boundary per scan iteration (VERDICT r4 #3b).
    spatial = int(os.environ.get("MH_SPATIAL", "0") or "0")
    if spatial == 1:
        # backward compat: MH_SPATIAL used to be a boolean flag whose "1"
        # meant the 2-way spatial mesh; a 1-way spatial axis is invalid
        # (MeshConfig rejects it), so keep the old meaning
        spatial = 2
    # MH_PALLAS=1: ring x flash — each hop's fold runs the flash kernels
    # (interpret mode on CPU devices), and the backward is the custom
    # grad-homing vjp (ops/pallas_attention.py::_ring_flash_vjp_bwd)
    use_pallas = os.environ.get("MH_PALLAS") == "1"
    # MH_NAN=abort|rollback: arm the per-step NaN gate (consensus every
    # step under multi-host) with the named policy — the parity A/B for
    # ISSUE 4's multi-host rollback (test_multihost.py); unset keeps the
    # default config (gate at its 100-step cadence, effectively off here)
    nan = os.environ.get("MH_NAN", "")
    nan_kw = {}
    if nan:
        # save_summaries_secs=0: every step gets a scalar row, so the A/B
        # compares deterministic step sets — the default 10 s wall-clock
        # throttle makes row PRESENCE timing-dependent and the comparison
        # flaky
        nan_kw = dict(nan_policy=nan, nan_check_steps=1,
                      save_summaries_secs=0.0)
        if nan == "rollback":
            nan_kw.update(rollback_snapshot_steps=2, max_rollbacks=2,
                          rollback_lr_backoff=0.5)
    from dcgan_tpu.config import MeshConfig

    cfg = TrainConfig(
        model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                          compute_dtype="float32",
                          attn_res=8 if spatial else 0,
                          use_pallas=use_pallas),
        mesh=(MeshConfig(model=spatial, spatial=True) if spatial
              else MeshConfig()),
        batch_size=16,                       # global; 8 per process
        backend=backend,
        checkpoint_dir=os.path.join(workdir, "ckpt"),
        sample_dir=os.path.join(workdir, "samples"),
        sample_every_steps=4 if spc > 1 else 3,  # replicated sample()
        activation_summary_steps=2,          # exercises the summarize program
        save_model_steps=10_000,             # periodic off; final save only
        log_every_steps=spc,
        steps_per_call=spc,
        # with spc > 1 also exercise the pre-staged device batch pool
        # through make_array_from_process_local_data on every process
        synthetic_device_cache=4 if spc > 1 else 0,
        sample_size=16,
        sample_grid=(4, 4),
        # MH_FID: the distributed in-training probe (VERDICT r2 #5) — the
        # budget splits 32/process, stats/reservoirs all-gather, every
        # process takes the best-save branch together
        fid_every_steps=2 if fid else 0,
        fid_num_samples=64 if fid else 2048,
        **nan_kw)
    state = train(cfg, synthetic_data=True, max_steps=4)
    step = int(jax.device_get(state["step"]))
    print(f"MH_OK pid={jax.process_index()} step={step}", flush=True)
    assert step == 4


if __name__ == "__main__":
    main()
    sys.exit(0)
