"""Progressive-resolution training plane (ISSUE 15).

Covers the schedule table (parse/validate/phase arithmetic), the
cross-phase state carry (bit-exact carried leaves on both backends and
under ZeRO residency), warmup-plan completeness + the zero-compile
switch contract (CompileCacheMonitor-pinned on the headline 64->128->256
ladder), loader re-bucketing with quarantine carry-over, mid-schedule
checkpoint resume (and the sidecar phase-tag cross-check), the fade
blend, and the single-phase parity A/B (a one-phase schedule IS the
existing trainer, byte-identical events modulo wall-clock).
"""

import dataclasses
import json
import os

import jax
import numpy as np
import pytest

from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
from dcgan_tpu.progressive import (
    PhaseRuntime,
    Rebucketer,
    carry_path,
    carry_state,
    parse_schedule,
    phase_data_cfg,
)


def _model(size=16, **kw):
    kw.setdefault("gf_dim", 8)
    kw.setdefault("df_dim", 8)
    kw.setdefault("compute_dtype", "float32")
    return ModelConfig(output_size=size, **kw)


def _cfg(tmp_path, size=16, spec="8:2,16:*", **kw):
    kw.setdefault("model", _model(size))
    kw.setdefault("batch_size", 8)
    kw.setdefault("tensorboard", False)
    kw.setdefault("sample_every_steps", 0)
    kw.setdefault("activation_summary_steps", 0)
    kw.setdefault("nan_check_steps", 0)
    kw.setdefault("save_summaries_secs", 0.0)
    kw.setdefault("save_model_secs", 1e9)
    kw.setdefault("log_every_steps", 1)
    kw.setdefault("checkpoint_dir", str(tmp_path / "ckpt"))
    kw.setdefault("sample_dir", str(tmp_path / "samples"))
    return TrainConfig(progressive=spec, **kw)


def _parse(spec, *, model=None, batch=8, max_steps=1000, **kw):
    return parse_schedule(spec, model=model or _model(),
                          batch_size=batch, max_steps=max_steps, **kw)


def _events(ckpt_dir):
    path = os.path.join(ckpt_dir, "events.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f]


# ---------------------------------------------------------------------------
# schedule parsing / validation / arithmetic
# ---------------------------------------------------------------------------

class TestSchedule:
    def test_parse_basic(self):
        s = _parse("8:4,16:*")
        assert [(p.resolution, p.steps, p.batch_size) for p in s.phases] \
            == [(8, 4, 8), (16, None, 8)]

    def test_per_phase_batch_override(self):
        s = _parse("8:4:16,16:*:4")
        assert [p.batch_size for p in s.phases] == [16, 4]

    def test_last_phase_must_be_star(self):
        with pytest.raises(ValueError, match="last progressive phase"):
            _parse("8:4,16:4")

    def test_star_only_on_last(self):
        with pytest.raises(ValueError, match="only valid on the last"):
            _parse("8:*,16:*")

    def test_resolutions_strictly_ascending(self):
        with pytest.raises(ValueError, match="strictly ascending"):
            _parse("16:4,16:*", model=_model(16))

    def test_resolution_must_be_stack_site(self):
        with pytest.raises(ValueError, match="model-stack site"):
            _parse("12:4,16:*")

    def test_last_resolution_must_match_model(self):
        with pytest.raises(ValueError, match="output_size"):
            _parse("8:4,32:*", model=_model(16))

    def test_steps_respect_steps_per_call(self):
        with pytest.raises(ValueError, match="steps_per_call"):
            _parse("8:3,16:*", steps_per_call=2)
        _parse("8:4,16:*", steps_per_call=2)  # aligned: fine

    def test_fixed_phases_must_leave_room(self):
        with pytest.raises(ValueError, match="never run"):
            _parse("8:1000,16:*", max_steps=1000)

    def test_fade_requires_room_and_per_step_dispatch(self):
        with pytest.raises(ValueError, match="steps_per_call=1"):
            _parse("8:4,16:4,32:*", model=_model(32), steps_per_call=2,
                   fade_steps=2)
        with pytest.raises(ValueError, match="exceeds phase"):
            _parse("8:4,16:4,32:*", model=_model(32), fade_steps=8)

    def test_phase_arithmetic_and_boundary_semantics(self):
        s = _parse("8:2,16:2,32:*", model=_model(32))
        assert s.starts(10) == [0, 2, 4]
        assert [s.index_for_dispatch(t, 10) for t in (0, 1, 2, 3, 4, 9)] \
            == [0, 0, 1, 1, 2, 2]
        # a state at completed-step 2 was PRODUCED by phase 0 (the switch
        # runs before the first new-phase dispatch)
        assert s.index_for_state(2, 10) == 0
        assert s.index_for_state(3, 10) == 1
        assert s.index_for_state(0, 10) == 0

    def test_alpha_ramp(self):
        s = _parse("8:2,16:*", fade_steps=4)
        assert s.alpha_at(0, 10) == 1.0   # first phase never fades
        assert s.alpha_at(2, 10) == pytest.approx(0.25)
        assert s.alpha_at(3, 10) == pytest.approx(0.5)
        assert s.alpha_at(5, 10) == pytest.approx(1.0)
        assert s.alpha_at(9, 10) == 1.0

    def test_validate_mesh_granule(self):
        s = _parse("8:2:6,16:*", model=_model(16))
        with pytest.raises(ValueError, match="does not divide"):
            s.validate_mesh({"data": 4, "model": 1}, spatial=False)

    def test_config_for_is_single_shape(self):
        cfg = _cfg_for_schedule()
        s = _parse("8:2,16:*")
        phase0 = s.config_for(cfg, 0)
        assert phase0.model.output_size == 8
        assert phase0.progressive == ""

    def test_config_validation_wires_the_parser(self, tmp_path):
        with pytest.raises(ValueError, match="last progressive phase"):
            _cfg(tmp_path, spec="8:4,16:4")
        with pytest.raises(ValueError, match="attn_res"):
            _cfg(tmp_path, size=32, spec="16:4,32:*",
                 model=_model(32, attn_res=16))
        with pytest.raises(ValueError, match="rollback_lr_backoff"):
            _cfg(tmp_path, nan_policy="rollback", nan_check_steps=1,
                 rollback_lr_backoff=0.5)
        with pytest.raises(ValueError, match="silent no-op"):
            _cfg(tmp_path, spec="", progressive_fade_steps=2)


def _cfg_for_schedule():
    return TrainConfig(model=_model(16), batch_size=8,
                       progressive="8:2,16:*", tensorboard=False)


# ---------------------------------------------------------------------------
# cross-phase state carry
# ---------------------------------------------------------------------------

class TestCarry:
    def test_dcgan_gen_stage_shift(self):
        # growing by one stage: old deconv{i} -> new deconv{i+1}; the
        # z-side top (proj/bn0) has no home; SN state shifts with its layer
        assert carry_path("params/gen/deconv1/w", arch="dcgan", shift=1) \
            == "params/gen/deconv2/w"
        assert carry_path("bn/gen/bn1/mean", arch="dcgan", shift=1) \
            == "bn/gen/bn2/mean"
        assert carry_path("opt/gen/0/0/mu/deconv2/w", arch="dcgan",
                          shift=1) == "opt/gen/0/0/mu/deconv3/w"
        assert carry_path("ema_gen/deconv1/b", arch="dcgan", shift=1) \
            == "ema_gen/deconv2/b"
        assert carry_path("bn/gen/sn_deconv1/u", arch="dcgan", shift=1) \
            == "bn/gen/sn_deconv2/u"
        assert carry_path("params/gen/proj/w", arch="dcgan", shift=1) \
            is None
        assert carry_path("params/gen/bn0/scale", arch="dcgan", shift=1) \
            is None

    def test_disc_and_scalars_identity(self):
        assert carry_path("params/disc/conv0/w", arch="dcgan", shift=1) \
            == "params/disc/conv0/w"
        assert carry_path("step", arch="dcgan", shift=1) == "step"
        assert carry_path("opt/disc/0/0/count", arch="dcgan", shift=1) \
            == "opt/disc/0/0/count"

    def test_non_dcgan_is_name_matched(self):
        assert carry_path("params/gen/deconv1/w", arch="resnet", shift=1) \
            == "params/gen/deconv1/w"

    @pytest.mark.parametrize("backend,zero", [("gspmd", 1),
                                              ("shard_map", 1),
                                              ("shard_map", 3)])
    def test_carried_leaves_bit_exact(self, tmp_path, backend, zero):
        """The issue's carry contract on live trees: carried leaves
        transfer bit-exactly (ZeRO-3 resident shards included — same
        path + shape + mesh => same spec, so the buffers carry verbatim),
        new-at-phase leaves keep their fresh init."""
        from dcgan_tpu.parallel import make_mesh

        cfg = _cfg(tmp_path, size=16, spec="8:2,16:*", backend=backend,
                   mesh=MeshConfig(data=2, zero_stage=zero))
        mesh = make_mesh(cfg.mesh, jax.devices()[:2])
        rt = PhaseRuntime(
            cfg, mesh,
            _parse("8:2,16:*", model=cfg.model, batch=cfg.batch_size),
            total_steps=10)
        st0 = rt.pt.init(jax.random.key(0))
        old = {p: np.asarray(jax.device_get(leaf)) for p, leaf in
               _flat(st0).items()}
        st1 = rt.advance(st0)
        assert rt.index == 1 and rt.last_carried > 0
        new = _flat(st1)
        hits = 0
        for path, arr in old.items():
            home = carry_path(path, arch="dcgan", shift=1)
            if home is None or home not in new:
                continue
            tgt = np.asarray(jax.device_get(new[home]))
            if tgt.shape != arr.shape:
                continue  # shape-guarded: fresh by design (head etc.)
            np.testing.assert_array_equal(tgt, arr, err_msg=home)
            hits += 1
        assert hits == rt.last_carried
        # a genuinely new leaf exists and is NOT the old one
        assert "params/gen/proj/w" in new

    def test_carry_state_shape_guard(self):
        # same name, different shape (the disc head) -> fresh init wins
        old = {"params": {"disc": {"head": {"w": np.ones((4, 1),
                                                         np.float32)}}}}
        fresh = {"params": {"disc": {"head": {"w": np.zeros((8, 1),
                                                            np.float32)}}}}
        merged, carried, staged = carry_state(old, fresh, arch="dcgan",
                                              shift=1)
        assert carried == 0 and not staged
        assert merged["params"]["disc"]["head"]["w"].shape == (8, 1)


def _flat(tree):
    from dcgan_tpu.elastic.rules import path_str

    return {path_str(p): leaf for p, leaf in
            jax.tree_util.tree_flatten_with_path(tree)[0]}


# ---------------------------------------------------------------------------
# warmup completeness + the zero-compile switch (the acceptance pin)
# ---------------------------------------------------------------------------

class TestWarmup:
    def test_plan_enumerates_every_phase(self, tmp_path):
        from dcgan_tpu.parallel import make_mesh
        from dcgan_tpu.train import warmup

        cfg = _cfg(tmp_path, size=32, spec="8:2,16:2,32:*",
                   sample_every_steps=100, activation_summary_steps=100,
                   progressive_fade_steps=2)
        mesh = make_mesh(cfg.mesh)
        rt = PhaseRuntime(
            cfg, mesh,
            _parse("8:2,16:2,32:*", model=cfg.model, fade_steps=2),
            total_steps=10)
        z = jax.random.uniform(jax.random.key(1), (8, cfg.model.z_dim))
        plan = rt.build_warmup_plan(warmup.state_example(rt.pt),
                                    sample_z=z)
        names = {n for n, _, _ in plan}
        # current phase rows keep their plain names (perf/compile_ms and
        # the coverage pins read unchanged); later phases suffix @r<res>
        assert {"init", "train_step", "state_copy", "sampler",
                "eval_losses", "summarize"} <= names
        for res in (16, 32):
            assert {f"init@r{res}", f"train_step@r{res}",
                    f"state_copy@r{res}", f"sampler@r{res}",
                    f"fade@r{res}"} <= names

    def test_headline_ladder_switches_with_zero_compile_requests(
            self, tmp_path, capsys):
        """THE acceptance criterion: a 64->128->256 smoke schedule on CPU
        completes with compile-request delta == 0 after AOT warmup across
        BOTH switches (CompileCacheMonitor-pinned via the trainer's
        per-switch printed delta — priming makes the zero literal, the
        PR 9 mechanism)."""
        from dcgan_tpu.train.trainer import train

        cfg = _cfg(tmp_path, size=256, spec="64:2,128:2,256:*",
                   batch_size=8, save_summaries_secs=1e9,
                   compile_cache_dir=str(tmp_path / "cache"),
                   aot_warmup=True)
        state = train(cfg, synthetic_data=True, max_steps=6)
        assert int(jax.device_get(state["step"])) == 6
        out = capsys.readouterr().out
        switches = [l for l in out.splitlines()
                    if "progressive phase" in l and "->" in l]
        assert len(switches) == 2, out[-2000:]
        for line in switches:
            assert "compile_requests_delta=0" in line, line

    def test_pipelined_progressive_warmup_primes_and_switches(
            self, tmp_path, capsys):
        """--pipeline_gd composes: prime() dispatches the stage programs
        (regression: the g_update metrics carry g_loss only — the prime
        sync must not assume d_loss) and the switch still reports zero
        compile requests."""
        from dcgan_tpu.train.trainer import train

        cfg = _cfg(tmp_path, size=16, spec="8:2,16:*", pipeline_gd=True,
                   save_summaries_secs=1e9,
                   compile_cache_dir=str(tmp_path / "cache"),
                   aot_warmup=True)
        state = train(cfg, synthetic_data=True, max_steps=4)
        assert int(jax.device_get(state["step"])) == 4
        out = capsys.readouterr().out
        assert "progressive warmup primed" in out
        switch = [l for l in out.splitlines()
                  if "progressive phase 1" in l]
        assert switch and "compile_requests_delta=0" in switch[0]


# ---------------------------------------------------------------------------
# loader re-bucketing + quarantine carry
# ---------------------------------------------------------------------------

class TestRebucket:
    def test_phase_data_cfg_substitutes_res(self, tmp_path):
        cfg = _cfg(tmp_path, data_dir="train_{res}",
                   sample_image_dir="held_{res}")
        p0 = phase_data_cfg(_parse("8:2,16:*").config_for(cfg, 0))
        assert p0.data_dir == "train_8" and p0.sample_image_dir == "held_8"
        plain = _cfg(tmp_path)
        assert phase_data_cfg(plain) is plain

    def test_reopen_closes_old_and_carries_tally(self):
        from dcgan_tpu.data import quarantine

        class FakeIt:
            def __init__(self):
                self.closed = False

            def close(self):
                self.closed = True

        opened = []

        def open_fn(cfg):
            it = FakeIt()
            opened.append(it)
            return it, None

        rb = Rebucketer(open_fn)
        cfg = _cfg_for_schedule()
        rb.open(cfg)
        base = quarantine.count()
        quarantine.record("shard-0", 7, "test corruption", budget=10_000)
        rb.reopen(cfg)
        assert opened[0].closed and not opened[1].closed
        # the process-global tally rode across the re-open
        assert rb.last_tally == base + 1
        assert rb.reopens == 1
        rb.close()
        assert opened[1].closed

    def test_real_data_rebucket_with_quarantine_budget(self, tmp_path):
        """End-to-end: per-resolution TFRecord dirs (the {res} pattern),
        one corrupt record in EACH, a budget spanning the run — the
        switch re-opens the loader at the new decode size and the
        quarantine counter accumulates across phases instead of
        resetting."""
        from dcgan_tpu.data.synthetic import write_image_tfrecords
        from dcgan_tpu.testing.chaos import corrupt_tfrecord_payload
        from dcgan_tpu.train.trainer import train

        for res in (8, 16):
            paths = write_image_tfrecords(
                str(tmp_path / f"train_{res}"), num_examples=32,
                image_size=res, num_shards=1)
            corrupt_tfrecord_payload(paths[0], record_index=1)
        cfg = _cfg(tmp_path, size=16, spec="8:3,16:*",
                   data_dir=str(tmp_path / "train_{res}"),
                   max_corrupt_records=100, shuffle_buffer=8,
                   num_loader_threads=1)
        state = train(cfg, synthetic_data=False, max_steps=6)
        assert int(jax.device_get(state["step"])) == 6
        counts = [e["values"]["data/corrupt_records"]
                  for e in _events(cfg.checkpoint_dir)
                  if e["kind"] == "scalars"
                  and "data/corrupt_records" in e["values"]]
        assert counts and max(counts) >= 2, counts  # both dirs' corruption


# ---------------------------------------------------------------------------
# checkpoint resume across the schedule
# ---------------------------------------------------------------------------

class TestResume:
    def test_mid_schedule_resume_lands_in_right_phase(self, tmp_path,
                                                      capsys):
        from dcgan_tpu.elastic import sidecar
        from dcgan_tpu.train.trainer import train

        cfg = _cfg(tmp_path, size=32, spec="8:2,16:2,32:*")
        train(cfg, synthetic_data=True, max_steps=3)   # stops inside r16
        payload = sidecar.read(cfg.checkpoint_dir, 3)
        assert payload["progressive"] == {"phase": 1, "resolution": 16}
        state = train(cfg, synthetic_data=True, max_steps=6)
        out = capsys.readouterr().out
        assert "starting in phase 1 (r16" in out
        assert "r16 -> r32" in out
        assert int(jax.device_get(state["step"])) == 6
        assert sidecar.read(cfg.checkpoint_dir, 6)["progressive"] \
            == {"phase": 2, "resolution": 32}

    def test_boundary_checkpoint_carries_pre_switch_tree(self, tmp_path,
                                                         capsys):
        """A save at exactly a phase boundary holds the OLD phase's tree
        (the switch runs before the first new-phase dispatch); the resume
        must template-match it, then switch immediately."""
        from dcgan_tpu.elastic import sidecar
        from dcgan_tpu.train.trainer import train

        cfg = _cfg(tmp_path, size=16, spec="8:2,16:*")
        train(cfg, synthetic_data=True, max_steps=2)
        assert sidecar.read(cfg.checkpoint_dir, 2)["progressive"] \
            == {"phase": 0, "resolution": 8}
        state = train(cfg, synthetic_data=True, max_steps=4)
        out = capsys.readouterr().out
        assert "starting in phase 0 (r8" in out
        assert "r8 -> r16" in out
        assert int(jax.device_get(state["step"])) == 4

    def test_consumers_resolve_mid_schedule_checkpoints(self, tmp_path):
        """generate/evals build their restore template through
        resolve_model_config: a checkpoint stopped mid-schedule holds an
        earlier phase's SHALLOWER tree, and the sidecar phase tag — not
        config.json's final architecture — must decide the model."""
        from dcgan_tpu.config import resolve_model_config
        from dcgan_tpu.train.trainer import train

        cfg = _cfg(tmp_path, size=32, spec="8:2,16:2,32:*")
        train(cfg, synthetic_data=True, max_steps=3)   # stopped inside r16
        resolved = resolve_model_config(cfg.checkpoint_dir)
        assert resolved.output_size == 16
        # an explicit flag still wins (the documented precedence)
        assert resolve_model_config(
            cfg.checkpoint_dir,
            overrides={"output_size": 32}).output_size == 32

    def test_schedule_change_between_runs_fails_loudly(self, tmp_path):
        from dcgan_tpu.train.trainer import train

        cfg = _cfg(tmp_path, size=16, spec="8:2,16:*")
        train(cfg, synthetic_data=True, max_steps=3)   # saved in phase 1
        moved = dataclasses.replace(cfg, progressive="8:4,16:*")
        with pytest.raises(ValueError, match="spec changed"):
            train(moved, synthetic_data=True, max_steps=6)


# ---------------------------------------------------------------------------
# fade
# ---------------------------------------------------------------------------

class TestFade:
    def test_fade_blend_semantics(self, tmp_path):
        from dcgan_tpu.parallel import make_mesh

        cfg = _cfg(tmp_path, progressive_fade_steps=2)
        mesh = make_mesh(cfg.mesh)
        rt = PhaseRuntime(cfg, mesh,
                          _parse("8:2,16:*", fade_steps=2), total_steps=10)
        rt.index = 1
        fade = rt.fade_program()
        x = jax.random.uniform(jax.random.key(0), (8, 16, 16, 3))
        np.testing.assert_allclose(np.asarray(fade(x, np.float32(1.0))),
                                   np.asarray(x), rtol=1e-6)
        low = np.asarray(fade(x, np.float32(0.0)))
        # alpha=0 is pure previous-resolution content: 2x2 blocks constant
        np.testing.assert_allclose(low[:, 0::2, 0::2], low[:, 1::2, 1::2],
                                   rtol=1e-5)

    def test_fade_run_completes_and_logs_alpha(self, tmp_path):
        from dcgan_tpu.train.trainer import train

        cfg = _cfg(tmp_path, size=16, spec="8:2,16:*",
                   progressive_fade_steps=2)
        state = train(cfg, synthetic_data=True, max_steps=6)
        assert int(jax.device_get(state["step"])) == 6
        alphas = [e["values"]["progressive/alpha"]
                  for e in _events(cfg.checkpoint_dir)
                  if e["kind"] == "scalars"
                  and "progressive/alpha" in e["values"]]
        assert alphas and all(0 < a < 1 for a in alphas)


# ---------------------------------------------------------------------------
# parity: a single-phase schedule IS the existing trainer
# ---------------------------------------------------------------------------

class TestParity:
    def test_single_phase_schedule_byte_identical_events(self, tmp_path):
        from dcgan_tpu.train.trainer import train

        def run(sub, spec):
            cfg = _cfg(tmp_path / sub, size=16, spec=spec,
                       nan_check_steps=2)
            train(cfg, synthetic_data=True, max_steps=6)
            lines = []
            for e in _events(cfg.checkpoint_dir):
                # wall-clock fields differ across ANY two runs (the same
                # convention as the async-vs-inline parity A/B); every
                # deterministic byte — kinds, steps, losses, histograms,
                # and crucially the KEY SET — must match exactly
                e.pop("time", None)
                if e["kind"] == "scalars":
                    e["values"] = {k: v for k, v in e["values"].items()
                                   if not k.startswith("perf/")}
                lines.append(json.dumps(e, sort_keys=True))
            return lines

        assert run("plain", "") == run("prog", "16:*")

    def test_progressive_keys_present_in_multi_phase_runs(self, tmp_path):
        from dcgan_tpu.train.event_keys import EVENT_KEYS
        from dcgan_tpu.train.trainer import train

        cfg = _cfg(tmp_path, size=16, spec="8:2,16:*")
        train(cfg, synthetic_data=True, max_steps=4)
        keys = set()
        for e in _events(cfg.checkpoint_dir):
            if e["kind"] == "scalars":
                keys |= {k for k in e["values"]
                         if k.startswith("progressive/")}
        assert {"progressive/phase", "progressive/resolution",
                "progressive/switch_ms"} <= keys
        for k in keys:   # every emitted key is inventory-declared
            assert k in EVENT_KEYS, k

    def test_counter_snapshot_has_phase_field(self):
        from dcgan_tpu.utils.metrics import CounterSnapshot

        assert CounterSnapshot().as_dict()["progressive_phase"] == 0
