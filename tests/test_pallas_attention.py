"""Flash-attention Pallas kernels (ops/pallas_attention.py): exactness vs the
dense reference, forward and backward, plus the attn_apply(use_pallas=True)
routing and a full train step on the fused path. Off-TPU the kernels run in
interpret mode — the same code path the chip compiles."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # see pytest.ini: excluded from the smoke tier

from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
from dcgan_tpu.utils.backend import shard_map
from dcgan_tpu.ops.attention import (
    attn_apply,
    attn_init,
    full_attention,
    ring_attention,
)
from dcgan_tpu.ops.pallas_attention import flash_attention
from dcgan_tpu.train import make_train_step


def qkv(B=2, S=256, d=8, dv=32, seed=0):
    k0 = jax.random.key(seed)
    return tuple(
        jax.random.normal(jax.random.fold_in(k0, i), (B, S, dim))
        for i, dim in enumerate((d, d, dv)))


class TestFlashAttention:
    @pytest.mark.parametrize("S", [128, 192, 256])
    def test_forward_matches_dense(self, S):
        q, k, v = qkv(S=S)
        scale = q.shape[-1] ** -0.5
        ref = full_attention(q, k, v, scale=scale)
        out = flash_attention(q, k, v, scale)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-6)

    def test_gradients_match_dense(self):
        q, k, v = qkv()
        scale = q.shape[-1] ** -0.5

        def dense(q, k, v):
            return jnp.sum(full_attention(q, k, v, scale=scale) ** 2)

        def flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, scale) ** 2)

        g_ref = jax.grad(dense, argnums=(0, 1, 2))(q, k, v)
        g_fl = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_fl):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=2e-5)

    def test_extreme_logits_stay_finite(self):
        # the online softmax must survive rows whose max logit is huge
        q, k, v = qkv(S=128)
        q = q * 100.0
        out = flash_attention(q, k, v, q.shape[-1] ** -0.5)
        assert np.all(np.isfinite(np.asarray(out)))

    def test_bf16_inputs(self):
        q, k, v = (t.astype(jnp.bfloat16) for t in qkv(S=128))
        scale = q.shape[-1] ** -0.5
        out = flash_attention(q, k, v, scale)
        ref = full_attention(q, k, v, scale=scale)
        assert out.dtype == jnp.float32  # f32 accumulation contract
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-2)

    def test_bad_tile_env_raises(self, monkeypatch):
        q, k, v = qkv(S=128)
        for bad in ("0", "-8", "garbage"):
            monkeypatch.setenv("DCGAN_FLASH_TQ", bad)
            with pytest.raises(ValueError, match="DCGAN_FLASH_TQ"):
                flash_attention(q, k, v, 0.1)

    @pytest.mark.parametrize("tq,tk", [("64", "32"), ("256", "128")])
    def test_tuned_tile_sizes_stay_exact(self, tq, tk, monkeypatch):
        # DCGAN_FLASH_TQ/TK are the chip-tuning knobs (read per call); any
        # divisor config must be bit-compatible with the default tiling
        q, k, v = qkv(S=256)
        scale = q.shape[-1] ** -0.5
        ref = full_attention(q, k, v, scale=scale)
        monkeypatch.setenv("DCGAN_FLASH_TQ", tq)
        monkeypatch.setenv("DCGAN_FLASH_TK", tk)
        out = flash_attention(q, k, v, scale)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-6)
        g_ref = jax.grad(lambda q: jnp.sum(
            full_attention(q, k, v, scale=scale) ** 2))(q)
        g_fl = jax.grad(lambda q: jnp.sum(
            flash_attention(q, k, v, scale) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g_fl), np.asarray(g_ref),
                                   atol=2e-5)


class TestRingFlash:
    """ring x flash composition (ops/pallas_attention.py::
    ring_flash_attention): sequence-parallel ring hops whose per-block fold
    runs the flash kernels — exactness vs full attention and vs the dense
    ring, forward and gradients, on the 8-virtual-device mesh."""

    def _mesh_and_spec(self, n):
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P

        mesh = Mesh(np.asarray(jax.devices()[:n]).reshape(1, n),
                    ("data", "model"))
        return mesh, P("data", "model", None)

    def _smap(self, fn, n):
        mesh, spec = self._mesh_and_spec(n)
        # check=False: pallas_call outputs carry no vma annotations
        # (same constraint as attn_apply's seq-parallel pallas routing)
        return shard_map(fn, mesh=mesh, in_specs=(spec,) * 3,
                             out_specs=spec, check=False)

    def test_forward_matches_dense_and_ring(self):
        import functools

        from dcgan_tpu.ops.pallas_attention import ring_flash_attention

        q, k, v = qkv(S=256, d=16, dv=32)
        scale = q.shape[-1] ** -0.5
        n = 8
        rf = self._smap(functools.partial(
            ring_flash_attention, scale=scale, axis_name="model",
            n_shards=n), n)
        ring = self._smap(functools.partial(
            ring_attention, axis_name="model", n_shards=n, scale=scale), n)
        dense = full_attention(q, k, v, scale=scale)
        np.testing.assert_allclose(np.asarray(rf(q, k, v)),
                                   np.asarray(dense), atol=2e-5)
        np.testing.assert_allclose(np.asarray(rf(q, k, v)),
                                   np.asarray(ring(q, k, v)), atol=2e-5)

    def test_gradients_match_dense(self):
        import functools

        from dcgan_tpu.ops.pallas_attention import ring_flash_attention

        q, k, v = qkv(S=128, d=8, dv=16)
        scale = q.shape[-1] ** -0.5
        n = 4
        rf = self._smap(functools.partial(
            ring_flash_attention, scale=scale, axis_name="model",
            n_shards=n), n)

        g_rf = jax.grad(lambda q, k, v: jnp.sum(rf(q, k, v) ** 2),
                        argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(
            lambda q, k, v: jnp.sum(
                full_attention(q, k, v, scale=scale) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_rf):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=5e-5)

    def test_single_shard_is_flash(self):
        from dcgan_tpu.ops.pallas_attention import ring_flash_attention

        q, k, v = qkv(S=128)
        scale = q.shape[-1] ** -0.5
        out = ring_flash_attention(q, k, v, scale=scale, axis_name="model",
                                   n_shards=1)
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(full_attention(q, k, v, scale=scale)), atol=2e-6)

    def test_attn_apply_routes_ring_through_flash(self):
        mesh, _ = self._mesh_and_spec(8)
        params = attn_init(jax.random.key(0), 16)
        params = dict(params, gamma=jnp.asarray(0.7))
        x = jax.random.normal(jax.random.key(1), (2, 16, 16, 16))
        dense_ring = attn_apply(params, x, seq_mesh=mesh,
                                seq_strategy="ring")
        flash_ring = attn_apply(params, x, seq_mesh=mesh,
                                seq_strategy="ring", use_pallas=True)
        np.testing.assert_allclose(np.asarray(flash_ring),
                                   np.asarray(dense_ring), atol=1e-5)


class TestFusedAttnApply:
    def test_use_pallas_matches_dense_block(self):
        params = attn_init(jax.random.key(0), 16)
        params = dict(params, gamma=jnp.asarray(0.5))
        x = jax.random.normal(jax.random.key(1), (2, 16, 16, 16))
        dense = attn_apply(params, x)
        fused = attn_apply(params, x, use_pallas=True)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(dense),
                                   atol=1e-5)

    def test_train_step_on_fused_path(self):
        cfg = TrainConfig(
            model=ModelConfig(output_size=16, gf_dim=8, df_dim=8, attn_res=8,
                              compute_dtype="float32", use_pallas=True),
            batch_size=8, mesh=MeshConfig(data=1))
        fns = make_train_step(cfg)
        state = fns.init(jax.random.key(0))
        xs = jnp.asarray(np.tanh(np.random.default_rng(0).normal(
            size=(8, 16, 16, 3))).astype(np.float32))
        state, metrics = jax.jit(fns.train_step)(state, xs, jax.random.key(1))
        assert int(state["step"]) == 1
        for v in metrics.values():
            assert np.isfinite(float(v))

    def test_fused_step_matches_unfused(self):
        base = ModelConfig(output_size=16, gf_dim=8, df_dim=8, attn_res=8,
                           compute_dtype="float32")
        xs = jnp.asarray(np.tanh(np.random.default_rng(0).normal(
            size=(8, 16, 16, 3))).astype(np.float32))
        results = []
        for use_pallas in (False, True):
            cfg = TrainConfig(model=dataclasses.replace(
                base, use_pallas=use_pallas), batch_size=8,
                mesh=MeshConfig(data=1))
            fns = make_train_step(cfg)
            state = fns.init(jax.random.key(0))
            state, metrics = jax.jit(fns.train_step)(state, xs,
                                                     jax.random.key(1))
            results.append((state, metrics))
        (_, m_ref), (_, m_fused) = results
        for k in m_ref:
            np.testing.assert_allclose(float(m_fused[k]), float(m_ref[k]),
                                       rtol=1e-4, err_msg=k)
