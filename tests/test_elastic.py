"""Elastic topology (ISSUE 12): the sharding-rule engine, the checkpoint
sharding sidecar, and the cross-mesh resharding restore.

The engine must reproduce the retired hand-built derivation bit-for-bit
(the committed semantic manifest's program fingerprints ride on the spec
objects); the sidecar must record the saving topology for every sharded
save; `restore_latest` must reshard across mesh/process changes while the
same-topology path stays byte-identical in behavior (sidecar present,
reshard not taken, no elastic/* keys). The full cross-process drill lives
in tools/chaos_drill.py (elastic-shrink / elastic-grow, the shrink smoke
pinned by tests/test_tools.py)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
from dcgan_tpu.elastic import rules, sidecar
from dcgan_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
from dcgan_tpu.train.steps import init_train_state

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh_of(n: int) -> Mesh:
    return Mesh(np.asarray(jax.devices()[:n]).reshape(n, 1),
                (DATA_AXIS, MODEL_AXIS))


# ONE variant list for the whole elastic surface: DCG011's coverage audit
# (analysis/semantic.py) and the engine-vs-oracle equivalence below must
# cover the same structural union of trainable families, so the list is
# defined once, there.
def _variants():
    from dcgan_tpu.analysis.semantic import spec_coverage_variants

    return dict(spec_coverage_variants())


def _state_shapes(variant: str):
    cfg = _variants()[variant]
    return jax.eval_shape(lambda k: init_train_state(k, cfg),
                          jax.random.key(0))


# -- the retired hand-built derivation, kept verbatim as the equivalence
# -- oracle: the engine must match it spec-object-for-spec-object ---------

def _oracle_spec_for_leaf(path, leaf, model_size):
    names = [p.key for p in path if hasattr(p, "key")]
    shape = getattr(leaf, "shape", ())
    if not names or len(shape) == 0:
        return P()

    def ok(dim):
        return shape[dim] % model_size == 0

    is_weight = names[-1] == "w"
    if is_weight and len(shape) == 4 and ok(3):
        return P(None, None, None, MODEL_AXIS)
    if is_weight and len(shape) == 2:
        if "proj" in names and ok(1):
            return P(None, MODEL_AXIS)
        if "head" in names and ok(0):
            return P(MODEL_AXIS, None)
    return P()


def _oracle_insert_data_axis(spec, shape, data_size):
    parts = list(spec) + [None] * (len(shape) - len(spec))
    for d, (axis, size) in enumerate(zip(parts, shape)):
        if axis is None and size >= data_size and size % data_size == 0:
            parts[d] = DATA_AXIS
            return P(*parts)
    return spec


def _oracle_state_shardings(state_shapes, mesh, *, spatial=False,
                            shard_opt=False):
    model_size = mesh.shape[MODEL_AXIS]
    data_size = mesh.shape[DATA_AXIS]

    def to_sharding(path, leaf):
        spec = P() if spatial else _oracle_spec_for_leaf(path, leaf,
                                                         model_size)
        if shard_opt and path and getattr(path[0], "key", None) == "opt":
            spec = _oracle_insert_data_axis(spec,
                                            getattr(leaf, "shape", ()),
                                            data_size)
        return NamedSharding(mesh, spec)
    return jax.tree_util.tree_map_with_path(to_sharding, state_shapes)


class TestRuleEngineEquivalence:
    """The regex table resolved against a mesh == the retired hand-built
    walk, spec OBJECT for spec object (not just placement-equivalent:
    P() vs P(None) would move every committed program fingerprint)."""

    @pytest.mark.parametrize("variant", sorted(_variants()))
    @pytest.mark.parametrize("mesh_cfg,spatial", [
        (MeshConfig(), False),
        (MeshConfig(model=2), False),
        (MeshConfig(model=4), False),
        (MeshConfig(model=2, spatial=True), True),
    ], ids=["dp8", "dp4xtp2", "dp2xtp4", "dp4xsp2"])
    @pytest.mark.parametrize("shard_opt", [False, True],
                             ids=["plain", "zero1"])
    def test_specs_match_oracle(self, variant, mesh_cfg, spatial,
                                shard_opt):
        from dcgan_tpu.parallel.sharding import state_shardings

        shapes = _state_shapes(variant)
        mesh = make_mesh(mesh_cfg)
        want = _oracle_state_shardings(shapes, mesh, spatial=spatial,
                                       shard_opt=shard_opt)
        got = state_shardings(shapes, mesh, spatial=spatial,
                              shard_opt=shard_opt)
        for (path, a), b in zip(jax.tree_util.tree_leaves_with_path(want),
                                jax.tree_util.tree_leaves(got)):
            assert a.spec == b.spec, (
                f"{jax.tree_util.keystr(path)}: oracle {a.spec} != "
                f"engine {b.spec}")


class TestRuleTable:
    def test_exact_one_coverage_every_family(self):
        """DCG011's contract, asserted directly: every leaf of every model
        family's full train state matches exactly one rule row."""
        from dcgan_tpu.analysis.semantic import check_spec_coverage

        assert check_spec_coverage() == []

    def test_unmatched_leaf_raises(self):
        with pytest.raises(ValueError, match="no sharding rule matches"):
            rules.logical_spec("params/gen/mystery_layer/q", 2)

    def test_rank_gates_sharded_rows(self):
        """A sharded row applies only at its own rank: a hypothetical
        rank-3 'proj/w' must not silently take the rank-2 projection
        rule."""
        assert rules.matching_rules("params/gen/proj/w", 2)
        assert not rules.matching_rules("params/gen/proj/w", 3)

    def test_ambiguity_detected(self):
        table = ((r"/w$", (None, MODEL_AXIS)),
                 (r"proj/w$", (None, MODEL_AXIS)))
        assert len(rules.matching_rules("a/proj/w", 2, table)) == 2

    def test_opt_and_ema_paths_hit_param_rules(self):
        spec = rules.logical_spec("opt/gen/1/0/mu/proj/w", 2)
        assert tuple(spec) == (None, MODEL_AXIS)
        spec = rules.logical_spec("ema_gen/deconv1/w", 4)
        assert tuple(spec) == (None, None, None, MODEL_AXIS)

    def test_resolution_policies(self):
        mesh_shape = {DATA_AXIS: 4, MODEL_AXIS: 2}
        conv = rules.logical_spec("params/gen/deconv1/w", 4)
        # divisible out-channels shard; a non-divisible dim collapses the
        # WHOLE spec (the old single ok(dim) gate)
        assert rules.resolve_spec(conv, (5, 5, 16, 8), mesh_shape) == \
            (None, None, None, MODEL_AXIS)
        assert rules.resolve_spec(conv, (5, 5, 8, 3), mesh_shape) == ()
        # size-1 model axis keeps the axis name (spec-object parity with
        # the old derivation on data-parallel meshes)
        assert rules.resolve_spec(conv, (5, 5, 8, 3),
                                  {DATA_AXIS: 8, MODEL_AXIS: 1}) == \
            (None, None, None, MODEL_AXIS)
        # an axis the current mesh does not carry replicates
        assert rules.resolve_spec(conv, (5, 5, 16, 8),
                                  {DATA_AXIS: 4}) == ()
        # spatial replicates everything
        assert rules.resolve_spec(conv, (5, 5, 16, 8), mesh_shape,
                                  spatial=True) == ()
        # ZeRO-1 inserts the data axis on the first dividing dim of
        # optimizer-state leaves only
        bias = rules.logical_spec("opt/gen/1/0/mu/proj/b", 1)
        assert rules.resolve_spec(bias, (256,), mesh_shape,
                                  shard_opt=True, is_opt=True) == \
            (DATA_AXIS,)
        assert rules.resolve_spec(bias, (256,), mesh_shape,
                                  shard_opt=True, is_opt=False) == ()

    def test_sidecar_specs_round_trip_through_engine(self):
        """state_partition_specs (what a sidecar would resolve on a target
        mesh) agrees with the NamedSharding tree the backends build."""
        from dcgan_tpu.parallel.sharding import state_shardings

        shapes = _state_shapes("dcgan")
        mesh = make_mesh(MeshConfig(model=2))
        table = rules.state_partition_specs(shapes, dict(mesh.shape))
        sh = state_shardings(shapes, mesh)
        for path, leaf in jax.tree_util.tree_leaves_with_path(sh):
            p = rules.path_str(path)
            assert P(*table[p]) == leaf.spec, p


def _small_tree(mesh: Mesh):
    sh = NamedSharding(mesh, P(DATA_AXIS))
    rep = NamedSharding(mesh, P())
    return {"params": {"gen": {"proj": {
                "w": jax.device_put(
                    jnp.arange(32, dtype=jnp.float32).reshape(8, 4), sh)}}},
            "step": jax.device_put(jnp.asarray(3, jnp.int32), rep)}


class TestSidecar:
    def test_payload_records_topology_and_specs(self):
        payload = sidecar.build_payload(_small_tree(_mesh_of(2)))
        assert payload["version"] == sidecar.VERSION
        assert payload["process_count"] == 1
        assert payload["mesh"] == {"axes": ["data", "model"],
                                   "sizes": [2, 1]}
        assert payload["specs"]["params/gen/proj/w"] == ["data", None]
        assert payload["specs"]["step"] == []

    def test_host_tree_yields_no_payload(self):
        assert sidecar.build_payload({"a": np.zeros(3)}) is None

    def test_mismatch_detection(self):
        tree = _small_tree(_mesh_of(2))
        payload = sidecar.build_payload(tree)
        assert sidecar.topology_mismatch(payload, tree) is None
        assert "8" in sidecar.topology_mismatch(
            payload, _small_tree(_mesh_of(8)))
        bumped = dict(payload, process_count=2)
        assert "processes 2 -> 1" in sidecar.topology_mismatch(bumped, tree)
        # a host tree can state no topology: never a mismatch
        assert sidecar.topology_mismatch(payload,
                                         {"a": np.zeros(3)}) is None


class TestCheckpointerReshard:
    def _ckpt(self, tmp_path):
        from dcgan_tpu.utils.checkpoint import Checkpointer

        return Checkpointer(str(tmp_path / "ck"), async_save=False)

    def test_sidecar_written_beside_manifest(self, tmp_path):
        ck = self._ckpt(tmp_path)
        ck.save(3, _small_tree(_mesh_of(2)))
        ck.wait()
        path = sidecar.sidecar_path(ck.directory, 3)
        assert os.path.exists(path)
        assert os.path.exists(os.path.join(ck.directory, "integrity",
                                           "3.json"))
        ck.close()

    def test_device_path_reshard(self, tmp_path):
        """Same process count, different mesh: the restore read is
        directed at the new NamedShardings; values and target shardings
        both exact."""
        ck = self._ckpt(tmp_path)
        ck.save(3, _small_tree(_mesh_of(2)))
        ck.wait()
        target = _small_tree(_mesh_of(8))
        restored = ck.restore_latest(target)
        assert restored is not None
        assert ck.last_reshard is not None
        assert ck.last_reshard["host_stage"] == 0.0
        assert ck.last_reshard["saved_devices"] == 2.0
        w = restored["params"]["gen"]["proj"]["w"]
        assert w.sharding == target["params"]["gen"]["proj"]["w"].sharding
        np.testing.assert_array_equal(
            np.asarray(w), np.arange(32, dtype=np.float32).reshape(8, 4))
        ck.close()

    def test_host_path_reshard(self, tmp_path):
        """A process-count change (simulated by editing the sidecar — one
        process cannot BE two) takes the host-staged path: numpy restore +
        per-shard upload, same values/shardings."""
        ck = self._ckpt(tmp_path)
        ck.save(3, _small_tree(_mesh_of(2)))
        ck.wait()
        path = sidecar.sidecar_path(ck.directory, 3)
        payload = json.load(open(path))
        payload["process_count"] = 2
        json.dump(payload, open(path, "w"))
        target = _small_tree(_mesh_of(8))
        restored = ck.restore_latest(target)
        assert ck.last_reshard is not None
        assert ck.last_reshard["host_stage"] == 1.0
        w = restored["params"]["gen"]["proj"]["w"]
        assert w.sharding == target["params"]["gen"]["proj"]["w"].sharding
        np.testing.assert_array_equal(
            np.asarray(w), np.arange(32, dtype=np.float32).reshape(8, 4))
        ck.close()

    def test_same_topology_takes_default_path(self, tmp_path):
        ck = self._ckpt(tmp_path)
        ck.save(3, _small_tree(_mesh_of(2)))
        ck.wait()
        restored = ck.restore_latest(_small_tree(_mesh_of(2)))
        assert restored is not None
        assert ck.last_reshard is None  # sidecar present, path untaken
        ck.close()

    def test_reshard_preserves_quarantine_fallback(self, tmp_path):
        """A corrupt newest step still quarantines and falls back on the
        reshard path — the verified-restore contract is topology-blind."""
        from dcgan_tpu.testing.chaos import truncate_file

        ck = self._ckpt(tmp_path)
        tree = _small_tree(_mesh_of(2))
        ck.save(3, tree)
        ck.wait()
        t2 = {"params": {"gen": {"proj": {"w": tree["params"]["gen"][
            "proj"]["w"] * 2}}}, "step": tree["step"]}
        ck.save(4, t2)
        ck.wait()
        files = []
        for root, _, names in os.walk(os.path.join(ck.directory, "4")):
            files += [os.path.join(root, n) for n in names]
        truncate_file(max(files, key=os.path.getsize))
        restored = ck.restore_latest(_small_tree(_mesh_of(8)))
        assert restored is not None
        assert os.path.isdir(os.path.join(ck.directory, "4.corrupt"))
        assert ck.last_reshard is not None  # step 3 resharded
        np.testing.assert_array_equal(
            np.asarray(restored["params"]["gen"]["proj"]["w"]),
            np.arange(32, dtype=np.float32).reshape(8, 4))
        ck.close()

    def test_delete_steps_after_removes_sidecar(self, tmp_path):
        ck = self._ckpt(tmp_path)
        ck.save(3, _small_tree(_mesh_of(2)))
        ck.wait()
        ck.save(5, _small_tree(_mesh_of(2)), force=True)
        ck.wait()
        assert os.path.exists(sidecar.sidecar_path(ck.directory, 5))
        dropped = ck.delete_steps_after(3)
        assert dropped == [5]
        assert not os.path.exists(sidecar.sidecar_path(ck.directory, 5))
        assert os.path.exists(sidecar.sidecar_path(ck.directory, 3))
        ck.close()


class TestZeroCrossStageRestore:
    """ISSUE 13 satellite: a zero_stage=3 checkpoint — params, EMA, and
    both Adam moments resident data-SHARDED over 2 devices — restores at
    zero_stage=1 on 1 device (and vice versa) through the PR 11 reshard
    path, every leaf bit-exact. The sidecar's per-leaf specs already
    carry the information; the ZeRO layout is a placement, not a format.
    Slow: four multi-device ParallelTrain compiles."""

    def _pt(self, stage, ndev):
        from dcgan_tpu.parallel import make_parallel_train

        cfg = TrainConfig(
            model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                              compute_dtype="float32"),
            batch_size=8, mesh=MeshConfig(data=ndev, zero_stage=stage))
        return make_parallel_train(cfg, _mesh_of(ndev))

    @pytest.mark.slow
    @pytest.mark.parametrize("direction", ["zero3to1", "zero1to3"])
    def test_cross_stage_cross_mesh_restore_bit_exact(self, tmp_path,
                                                      direction):
        from dcgan_tpu.utils.checkpoint import Checkpointer

        src_stage, src_dev, dst_stage, dst_dev = \
            (3, 2, 1, 1) if direction == "zero3to1" else (1, 1, 3, 2)
        pt = self._pt(src_stage, src_dev)
        state = pt.init(jax.random.key(0))
        rng = np.random.default_rng(0)
        xs = jnp.asarray(np.tanh(rng.normal(size=(8, 16, 16, 3)))
                         .astype(np.float32))
        for i in range(2):
            state, _ = pt.step(state, xs,
                               jax.random.fold_in(jax.random.key(1), i))
        host_src = jax.device_get(state)
        ck = Checkpointer(str(tmp_path / "ck"), async_save=False)
        ck.save(2, state)
        ck.wait()
        # the sidecar recorded the ZeRO residency as per-leaf specs
        payload = sidecar.read(ck.directory, 2)
        assert payload is not None
        mu_spec = payload["specs"]["opt/disc/1/0/mu/conv1/w"]
        w_spec = payload["specs"]["params/disc/conv1/w"]
        if src_stage >= 3:
            assert any(a == DATA_AXIS or (isinstance(a, list)
                                          and DATA_AXIS in a)
                       for a in mu_spec if a)
            assert any(a == DATA_AXIS or (isinstance(a, list)
                                          and DATA_AXIS in a)
                       for a in w_spec if a)

        pt2 = self._pt(dst_stage, dst_dev)
        target = pt2.init(jax.random.key(7))
        restored = ck.restore_latest(target)
        assert restored is not None
        # the mesh changed (2 <-> 1 devices), so the reshard path ran
        assert ck.last_reshard is not None
        assert ck.last_reshard["saved_devices"] == float(src_dev)
        # restored leaves carry the TARGET stage's shardings...
        mu_t = target["opt"]["disc"][1][0].mu["conv1"]["w"]
        mu_r = restored["opt"]["disc"][1][0].mu["conv1"]["w"]
        assert mu_r.sharding == mu_t.sharding
        # ...and every leaf's VALUES moved bit-exactly
        host_dst = jax.device_get(restored)
        for (path, a), b in zip(
                jax.tree_util.tree_leaves_with_path(host_src),
                jax.tree_util.tree_leaves(host_dst)):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b),
                err_msg=jax.tree_util.keystr(path))
        ck.close()


class TestSameTopologyParity:
    """The parity contract (ISSUE 12 satellite): on a SAME-topology
    save/resume, the sidecar machinery must be invisible — the resume's
    event stream is identical whether the sidecar exists or was deleted,
    and elastic/* keys never appear."""

    def _cfg(self, root, **kw):
        return TrainConfig(
            model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                              compute_dtype="float32"),
            batch_size=8, tensorboard=False, sample_every_steps=0,
            activation_summary_steps=0, save_summaries_secs=0.0,
            log_every_steps=1, save_model_secs=1e9,
            checkpoint_dir=str(root / "ckpt"),
            sample_dir=str(root / "samples"), **kw)

    def _events(self, root):
        cleaned = []
        with open(root / "ckpt" / "events.jsonl") as f:
            for line in f:
                e = json.loads(line)
                e.pop("time", None)
                if e["kind"] == "scalars":
                    e["values"] = {k: v for k, v in e["values"].items()
                                   if not k.startswith("perf/")}
                cleaned.append(e)
        return cleaned

    def test_resume_stream_identical_with_and_without_sidecar(
            self, tmp_path):
        from dcgan_tpu.train.trainer import train

        def run(sub, drop_sidecar):
            root = tmp_path / sub
            train(self._cfg(root), synthetic_data=True, max_steps=2)
            if drop_sidecar:
                removed = 0
                int_dir = root / "ckpt" / "integrity"
                for name in os.listdir(int_dir):
                    if name.endswith(".sharding.json"):
                        os.remove(int_dir / name)
                        removed += 1
                assert removed  # the save really produced sidecars
            train(self._cfg(root), synthetic_data=True, max_steps=4)
            return self._events(root)

        with_sidecar = run("with", drop_sidecar=False)
        without = run("without", drop_sidecar=True)
        assert with_sidecar == without
        for e in with_sidecar:
            if e["kind"] == "scalars":
                assert not any(k.startswith("elastic/")
                               for k in e["values"])


@pytest.mark.slow
class TestServeCrossTopology:
    """ISSUE 12 satellite: CheckpointSource cold-starts from a checkpoint
    saved on a DIFFERENT topology (a 2-device subprocess save served on
    the 8-device test mesh), restores through the sidecar reshard, and
    serves samples BIT-equal to the same weights placed directly on the
    serving mesh — the reshard moved bytes, not values."""

    _SAVER = """
import jax; jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)
import numpy as np
from dcgan_tpu.config import ModelConfig, TrainConfig
from dcgan_tpu.elastic.rules import path_str
from dcgan_tpu.train.trainer import train
cfg = TrainConfig(model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                                    compute_dtype="float32"),
                  batch_size=8, tensorboard=False, sample_every_steps=0,
                  save_summaries_secs=0.0, log_every_steps=1,
                  save_model_secs=1e9, checkpoint_dir=r"{ck}",
                  sample_dir=r"{sm}")
state = train(cfg, synthetic_data=True, max_steps=1)
flat = {{path_str(p): np.asarray(jax.device_get(v)) for p, v in
        jax.tree_util.tree_flatten_with_path(state)[0]}}
np.savez(r"{npz}", **flat)
print("SAVED", len(flat))
"""

    def test_cross_topology_cold_start_bit_equal(self, tmp_path):
        from dcgan_tpu.serve.buckets import BucketLadder, compile_buckets
        from dcgan_tpu.serve.sources import CheckpointSource

        ck = str(tmp_path / "ck")
        npz = str(tmp_path / "state.npz")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=2")
        res = subprocess.run(
            [sys.executable, "-c", self._SAVER.format(
                ck=ck, sm=str(tmp_path / "sm"), npz=npz)],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
        assert res.returncode == 0, (res.stdout[-800:], res.stderr[-800:])

        src = CheckpointSource(ck, max_batch=8)
        meta = src.prepare()
        assert "resharded" in meta, meta
        assert meta["resharded"]["saved_devices"] == 2
        # the resharded state's bytes == the saver's host dump
        host = np.load(npz)
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                src._state)[0]:
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(leaf)),
                host[rules.path_str(path)])
        ladder = BucketLadder([8], granule=src.granule)
        compiled, _ = compile_buckets(src.bucket_plan(ladder))
        src.bind(compiled)
        z = np.random.default_rng(7).uniform(
            -1, 1, (8, 100)).astype(np.float32)
        got = src.sample(8, z)

        # same-topology reference: identical weights placed directly on
        # the serving mesh (no checkpoint, no reshard), same program
        ref_src = CheckpointSource(ck, max_batch=8)
        ref_src.prepare()
        unflat = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(ref_src._state),
            [host[rules.path_str(p)] for p, _ in
             jax.tree_util.tree_flatten_with_path(ref_src._state)[0]])
        ref_src._state = jax.tree_util.tree_map(
            lambda a, like: jax.device_put(a, like.sharding),
            unflat, ref_src._state)
        ref_compiled, _ = compile_buckets(ref_src.bucket_plan(ladder))
        ref_src.bind(ref_compiled)
        ref = ref_src.sample(8, z)
        np.testing.assert_array_equal(got, ref)
