"""Reduced-precision ladder (ISSUE 17): the `--precision {f32,bf16,fp8}`
policy knob and everything downstream of it — config normalization, f32
master-moment layout, simulated-fp8 numerics, the int8 post-training-
quantization serving rung, telemetry surfacing, and the bf16 FID-parity
gate. The structural parity gate runs in the smoke tier (the ISSUE's
acceptance requires it in tier-1); the full FID run rides the slow tier."""

import dataclasses

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import pytest

from dcgan_tpu.config import ModelConfig, TrainConfig, config_from_dict, \
    config_to_dict
from dcgan_tpu.ops.pallas_fused import fake_quant_fp8


def _cfg(precision="", pallas_fused=False, **kw):
    return TrainConfig(
        model=ModelConfig(output_size=16, base_size=4, gf_dim=8, df_dim=8,
                          z_dim=8, use_pallas=pallas_fused,
                          pallas_fused=pallas_fused),
        batch_size=8, precision=precision, max_steps=100, **kw)


class TestPolicyConfig:
    """precision is ONE knob normalized into the model dtype/quant fields
    at construction, so checkpoints and config_from_dict reproduce the
    same model; setting the model fields by hand is rejected."""

    def test_bf16_policy(self):
        cfg = _cfg("bf16")
        assert cfg.model.compute_dtype == "bfloat16"
        assert cfg.model.param_dtype == "bfloat16"
        assert cfg.model.quant == ""

    def test_fp8_policy_adds_quant(self):
        cfg = _cfg("fp8")
        assert cfg.model.compute_dtype == "bfloat16"
        assert cfg.model.param_dtype == "bfloat16"
        assert cfg.model.quant == "fp8"

    def test_f32_policy_overrides_model_default(self):
        # the model's default compute dtype is bfloat16 — precision="f32"
        # must override it (one knob, one meaning), giving a true-f32 arm
        cfg = _cfg("f32")
        assert cfg.model.compute_dtype == "float32"
        assert cfg.model.param_dtype == "float32"

    def test_unset_leaves_model_alone(self):
        cfg = _cfg("")
        assert cfg.model.compute_dtype == "bfloat16"
        assert cfg.model.param_dtype == "float32"

    @pytest.mark.parametrize("precision", ["f32", "bf16", "fp8"])
    def test_dict_roundtrip_idempotent(self, precision):
        cfg = _cfg(precision)
        cfg2 = config_from_dict(config_to_dict(cfg))
        assert cfg2.precision == precision
        assert cfg2.model == cfg.model

    def test_invalid_precision_raises(self):
        with pytest.raises(ValueError, match="precision"):
            _cfg("fp16")

    def test_manual_model_quant_raises(self):
        with pytest.raises(ValueError, match="precision"):
            TrainConfig(model=ModelConfig(quant="fp8"), batch_size=8)


class TestMasterWeights:
    """bf16/fp8 keep an f32 master copy of the Adam FIRST moment
    (mu_dtype=f32); params and the sqrt-bound second moment stay in the
    param dtype. Verified structurally (eval_shape — no compute)."""

    def _state_shapes(self, precision, pallas_fused=False):
        from dcgan_tpu.train.steps import make_train_step

        cfg = _cfg(precision, pallas_fused)
        fns = make_train_step(cfg)
        return cfg, fns, jax.eval_shape(fns.init, jax.random.key(0))

    def _leaf_dtypes(self, state, match):
        return [(jtu.keystr(p), l.dtype)
                for p, l in jtu.tree_flatten_with_path(state)[0]
                if match in jtu.keystr(p)]

    def test_bf16_layout(self):
        _, _, state = self._state_shapes("bf16")
        params = self._leaf_dtypes(state["params"], "")
        assert params and all(d == jnp.bfloat16 for _, d in params)
        mu = self._leaf_dtypes(state["opt"], "mu")
        assert mu and all(d == jnp.float32 for _, d in mu)
        nu = self._leaf_dtypes(state["opt"], "nu")
        assert nu and all(d == jnp.bfloat16 for _, d in nu)

    def test_f32_has_no_split_layout(self):
        _, _, state = self._state_shapes("f32")
        for leaves in (self._leaf_dtypes(state["opt"], "mu"),
                       self._leaf_dtypes(state["opt"], "nu")):
            assert leaves and all(d == jnp.float32 for _, d in leaves)

    def test_master_leaf_census(self):
        from dcgan_tpu.elastic.rules import count_master_f32_leaves

        _, _, state = self._state_shapes("bf16")
        n_params = len(jtu.tree_leaves(state["params"]))
        assert count_master_f32_leaves(state) == n_params
        _, _, state_f = self._state_shapes("f32")
        assert count_master_f32_leaves(state_f) == 0
        _, _, state_d = self._state_shapes("")
        assert count_master_f32_leaves(state_d) == 0

    @pytest.mark.parametrize("precision,fused", [
        ("", False), ("bf16", False), ("bf16", True), ("fp8", True)])
    def test_train_step_dtype_invariance(self, precision, fused):
        # regression for the f32-cotangent bug: a single leaf changing
        # dtype across the step breaks lax.scan carries and donation
        # aliasing. The step must be a dtype-preserving state map under
        # EVERY policy x fusion combination.
        cfg, fns, state = self._state_shapes(precision, fused)
        img = jax.ShapeDtypeStruct((8, 16, 16, 3), jnp.float32)
        out, _ = jax.eval_shape(fns.train_step, state, img,
                                jax.random.key(1))
        ins = {jtu.keystr(p): l for p, l in
               jtu.tree_flatten_with_path(state)[0]}
        bad = [jtu.keystr(p) for p, l in jtu.tree_flatten_with_path(out)[0]
               if ins[jtu.keystr(p)].dtype != l.dtype]
        assert not bad, f"dtype drift across train_step: {bad}"


class TestFp8Numerics:
    def test_large_amax_stays_finite(self):
        # e4m3's max normal is 448 — an unscaled cast of 500 overflows to
        # NaN; the amax scaling must keep the round-trip finite
        x = jnp.array([500.0, -3.0, 0.25, 0.0])
        q = fake_quant_fp8(x)
        assert bool(jnp.all(jnp.isfinite(q)))
        np.testing.assert_allclose(q[0], 500.0, rtol=0.08)

    def test_relative_error_bound(self):
        x = jax.random.normal(jax.random.key(0), (512,))
        q = fake_quant_fp8(x)
        # 3 mantissa bits: worst-case relative rounding error 2^-4
        err = jnp.abs(q - x) / jnp.maximum(jnp.abs(x), 1e-3)
        assert float(jnp.max(err)) < 0.0726

    def test_preserves_dtype_shape_and_zero(self):
        x = jax.random.normal(jax.random.key(1), (4, 6), jnp.bfloat16)
        q = fake_quant_fp8(x)
        assert q.dtype == jnp.bfloat16 and q.shape == x.shape
        z = fake_quant_fp8(jnp.zeros((8,)))
        np.testing.assert_array_equal(z, jnp.zeros((8,)))

    def test_stage_gating_by_resolution(self):
        # fp8 operand quantization is scoped to stages whose feature maps
        # reach 64px — the boundary stages and every stage of small models
        # run clean bf16
        from dcgan_tpu.models.dcgan import _FP8_MIN_RES, _stage_quant

        cfg = ModelConfig(output_size=128, quant="fp8")
        assert _FP8_MIN_RES == 64
        assert _stage_quant(cfg, 32) == ""
        assert _stage_quant(cfg, 64) == "fp8"
        assert _stage_quant(cfg, 128) == "fp8"
        assert _stage_quant(ModelConfig(output_size=128), 128) == ""


class TestInt8Serving:
    """Post-training int8 rung (serve/quantize.py): symmetric per-output-
    channel round-trip of the weight kernels; biases/BN leaves exact."""

    def _params(self):
        from dcgan_tpu.models import gan_init

        mcfg = ModelConfig(output_size=16, base_size=4, gf_dim=8, df_dim=8,
                           z_dim=8)
        params, _ = gan_init(jax.random.key(0), mcfg)
        return params

    def test_report_and_error_bound(self):
        from dcgan_tpu.serve.quantize import quantize_dequantize_int8

        params = self._params()
        qp, report = quantize_dequantize_int8(params)
        assert report["scheme"] == "int8-sym-per-channel"
        assert report["quantized_leaves"] > 0
        assert 0 < report["max_rel_error"] < 0.02
        assert report["int8_bytes"] < report["orig_bytes"]
        assert report["worst_leaf"].endswith("/w")

    def test_only_weight_kernels_touched(self):
        from dcgan_tpu.serve.quantize import quantize_dequantize_int8

        params = self._params()
        qp, _ = quantize_dequantize_int8(params)
        for (path, a), (_, b) in zip(
                jtu.tree_flatten_with_path(params)[0],
                jtu.tree_flatten_with_path(qp)[0]):
            p = jtu.keystr(path)
            if p.endswith("['w']"):
                assert not bool(jnp.array_equal(a, b)), p
            else:
                np.testing.assert_array_equal(a, b, err_msg=p)


class TestTelemetry:
    def test_event_keys_registered(self):
        from dcgan_tpu.train.event_keys import EVENT_KEYS

        assert EVENT_KEYS["perf/precision/policy"] == "precision"
        assert EVENT_KEYS["perf/precision/master_f32_leaves"] == "precision"

    def test_counter_snapshot_field(self):
        from dcgan_tpu.utils.metrics import CounterSnapshot

        assert CounterSnapshot().master_f32_leaves == 0

    def test_flight_context_names_policy(self):
        from dcgan_tpu.train.flight_recorder import FlightRecorder
        from dcgan_tpu.train.trainer import _flight_context
        from dcgan_tpu.utils.profiling import StartupProfile

        fl = FlightRecorder("", capacity=0)
        ctx = _flight_context(_cfg("bf16"), StartupProfile(), fl)
        assert ctx["precision"] == "bf16"
        # the default policy must emit NOTHING — crash dumps under the
        # parity-pinned configuration stay byte-stable
        assert "precision" not in _flight_context(_cfg(""), StartupProfile(),
                                                  fl)


# ---------------------------------------------------------------------------
# FID-parity gate: the bf16 arm must land where the f32 arm lands
# ---------------------------------------------------------------------------

def _images(seed, n, size):
    return jnp.tanh(jax.random.normal(jax.random.key(seed), (n, size, size,
                                                             3)))


def _train_arm(precision, steps):
    from dcgan_tpu.train.steps import make_train_step

    fns = make_train_step(_cfg(precision))
    state = jax.jit(fns.init)(jax.random.key(0))
    step = jax.jit(fns.train_step)
    metrics = None
    for i in range(steps):
        state, metrics = step(state, _images(i, 8, 16),
                              jax.random.key(1000 + i))
    return fns, state, metrics


class TestFidParityGate:
    def test_bf16_structural_parity(self):
        """Smoke-tier gate: identical seeds/data, 4 steps per arm — the
        bf16 arm's samples and losses must track the f32 arm closely
        (measured drift ~2e-3 per pixel; bounds carry ~20x margin)."""
        fns_f, state_f, m_f = _train_arm("f32", 4)
        fns_b, state_b, m_b = _train_arm("bf16", 4)
        assert abs(float(m_f["d_loss"]) - float(m_b["d_loss"])) < 0.3
        assert abs(float(m_f["g_loss"]) - float(m_b["g_loss"])) < 0.3
        z = jax.random.uniform(jax.random.key(7), (64, 8),
                               minval=-1.0, maxval=1.0)
        a = np.asarray(fns_f.sample(state_f, z), np.float32)
        b = np.asarray(fns_b.sample(state_b, z), np.float32)
        assert b.dtype == np.float32 and a.shape == b.shape
        assert np.abs(a - b).mean() < 0.05
        assert abs(a.mean() - b.mean()) < 0.02
        assert abs(a.std() - b.std()) < 0.02

    @pytest.mark.slow
    def test_bf16_fid_parity(self):
        """Full gate: FID of each arm against the same synthetic real
        stream — the bf16 arm must score within 15% of f32 (measured gap
        ~0.15%; the bound covers seed-to-seed FID estimator noise)."""
        from dcgan_tpu.evals.job import compute_fid

        def _stream(seed, nb, n, size):
            for i in range(nb):
                yield np.asarray(_images(seed * 100 + i, n, size))

        fids = {}
        for prec in ("f32", "bf16"):
            fns, state, _ = _train_arm(prec, 4)
            r = compute_fid(lambda z: fns.sample(state, z),
                            _stream(9, 4, 64, 16), image_size=16,
                            z_dim=8, num_samples=256, batch_size=64)
            assert np.isfinite(r["fid"]) and r["fid"] > 0
            fids[prec] = r["fid"]
        assert abs(fids["bf16"] - fids["f32"]) <= 0.15 * fids["f32"]
