"""Model tests: G/D/sampler shapes, conditioning, 128x128 config, EMA-sampler
semantics (reference parity: distriubted_model.py:83-153)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcgan_tpu.config import ModelConfig
from dcgan_tpu.models import (
    discriminator_apply,
    discriminator_init,
    gan_init,
    generator_apply,
    generator_init,
    sampler_apply,
)

CFG = ModelConfig(compute_dtype="float32")  # f32 on CPU for numerics


@pytest.mark.slow
def test_generator_output_shape_and_range():
    p, s = generator_init(jax.random.key(0), CFG)
    z = jax.random.uniform(jax.random.key(1), (8, 100), minval=-1, maxval=1)
    img, s1 = generator_apply(p, s, z, cfg=CFG, train=True)
    assert img.shape == (8, 64, 64, 3)
    assert float(jnp.max(img)) <= 1.0 and float(jnp.min(img)) >= -1.0
    # BN state updated for bn0..bn3 (4 up layers -> 3 inner BNs + bn0)
    assert set(s1.keys()) == {"bn0", "bn1", "bn2", "bn3"}


@pytest.mark.slow
def test_generator_batch_size_not_hardcoded():
    """The reference hard-codes batch 64 into every deconv output_shape
    (distriubted_model.py:93-109); ours must follow the input batch."""
    p, s = generator_init(jax.random.key(0), CFG)
    for b in (1, 3, 16):
        z = jnp.zeros((b, 100))
        img, _ = generator_apply(p, s, z, cfg=CFG, train=True)
        assert img.shape == (b, 64, 64, 3)


def test_discriminator_shapes():
    p, s = discriminator_init(jax.random.key(0), CFG)
    x = jax.random.normal(jax.random.key(1), (8, 64, 64, 3))
    prob, logit, s1 = discriminator_apply(p, s, x, cfg=CFG, train=True)
    assert prob.shape == (8, 1) and logit.shape == (8, 1)
    np.testing.assert_allclose(np.asarray(prob),
                               np.asarray(jax.nn.sigmoid(logit)), rtol=1e-6)
    # stage 0 has no BN (reference: d_bn0 unused, SURVEY.md §2.4 #7)
    assert set(s1.keys()) == {"bn1", "bn2", "bn3"}
    assert "bn0" not in p


def test_sampler_uses_running_stats():
    """sampler == generator with train=False reading the EMA stats captured by
    train-mode calls — the reference's implicit coupling
    (distriubted_model.py:42,47) made explicit."""
    p, s = generator_init(jax.random.key(0), CFG)
    z = jax.random.uniform(jax.random.key(1), (4, 100), minval=-1, maxval=1)
    # advance the EMA with a few train steps
    for i in range(3):
        _, s = generator_apply(p, s, z + 0.1 * i, cfg=CFG, train=True)
    out1 = sampler_apply(p, s, z, cfg=CFG)
    out2, s_after = generator_apply(p, s, z, cfg=CFG, train=False)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2))
    # eval never mutates the running stats
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        s_after, s)
    # and train-mode output differs (batch stats vs EMA stats)
    out_train, _ = generator_apply(p, s, z, cfg=CFG, train=True)
    assert float(jnp.max(jnp.abs(out_train - out1))) > 1e-4


@pytest.mark.slow
def test_128x128_config():
    cfg = ModelConfig(output_size=128, compute_dtype="float32")
    assert cfg.num_up_layers == 5
    p, s = generator_init(jax.random.key(0), cfg)
    # top projection goes to gf*16 channels for the 5-stage stack
    assert p["proj"]["w"].shape == (100, 16 * 64 * 4 * 4)
    img, _ = generator_apply(p, s, jnp.zeros((2, 100)), cfg=cfg, train=True)
    assert img.shape == (2, 128, 128, 3)
    dp, ds = discriminator_init(jax.random.key(1), cfg)
    _, logit, _ = discriminator_apply(dp, ds, img, cfg=cfg, train=True)
    assert logit.shape == (2, 1)


def test_conditional_dcgan():
    """CIFAR-10-style class conditioning (BASELINE.json config #4; activates the
    reference's dead `y` arg, distriubted_model.py:83)."""
    cfg = ModelConfig(output_size=32, base_size=4, num_classes=10,
                      compute_dtype="float32")
    p, s = gan_init(jax.random.key(0), cfg)
    z = jnp.zeros((4, 100))
    y = jnp.array([0, 3, 7, 9])
    img, _ = generator_apply(p["gen"], s["gen"], z, cfg=cfg, train=True, labels=y)
    assert img.shape == (4, 32, 32, 3)
    _, logit, _ = discriminator_apply(p["disc"], s["disc"], img, cfg=cfg,
                                      train=True, labels=y)
    assert logit.shape == (4, 1)
    # different labels must produce different images for the same z
    img2, _ = generator_apply(p["gen"], s["gen"], z, cfg=cfg, train=True,
                              labels=jnp.array([1, 4, 8, 2]))
    assert float(jnp.max(jnp.abs(img - img2))) > 1e-4
    with pytest.raises(ValueError):
        generator_apply(p["gen"], s["gen"], z, cfg=cfg, train=True)


def test_conditional_bn_generator():
    """cBN (SAGAN/BigGAN): per-class [K, C] BN affine tables in G, gathered
    per example; the z-concat conditioning remains on top."""
    import dataclasses

    base = ModelConfig(output_size=32, base_size=4, num_classes=10,
                       compute_dtype="float32")
    cfg = dataclasses.replace(base, conditional_bn=True)
    p, s = gan_init(jax.random.key(0), cfg)
    assert p["gen"]["bn0"]["scale"].shape[0] == 10      # per-class tables
    assert p["disc"]["bn1"]["scale"].ndim == 1          # D stays plain BN
    assert s["gen"]["bn0"]["mean"].ndim == 1            # shared moments
    z = jnp.zeros((4, 100))
    img, _ = generator_apply(p["gen"], s["gen"], z, cfg=cfg, train=True,
                             labels=jnp.array([0, 3, 7, 9]))
    assert img.shape == (4, 32, 32, 3)
    # plain-BN config must keep vector tables (flag actually gates)
    p2, _ = gan_init(jax.random.key(0), base)
    assert p2["gen"]["bn0"]["scale"].ndim == 1
    with pytest.raises(ValueError, match="num_classes"):
        ModelConfig(num_classes=0, conditional_bn=True)


def test_gan_init_partitions_params():
    p, s = gan_init(jax.random.key(0), CFG)
    assert set(p.keys()) == {"gen", "disc"}
    assert set(s.keys()) == {"gen", "disc"}


def test_activation_capture():
    """capture= collects every post-activation tensor (the reference's
    _activation_summary taps, distriubted_model.py:75-80,94-110)."""
    p, s = gan_init(jax.random.key(0), CFG)
    z = jax.random.uniform(jax.random.key(1), (4, 100), minval=-1, maxval=1)
    g_cap, d_cap = {}, {}
    img, _ = generator_apply(p["gen"], s["gen"], z, cfg=CFG, train=True,
                             capture=g_cap)
    discriminator_apply(p["disc"], s["disc"], img, cfg=CFG, train=True,
                        capture=d_cap)
    # G: h0 (4x4 post-BN-relu), h1..h3 (inner deconvs), h4 (tanh output)
    assert set(g_cap.keys()) == {"h0", "h1", "h2", "h3", "h4"}
    assert g_cap["h0"].shape == (4, 4, 4, 512)
    assert g_cap["h4"].shape == (4, 64, 64, 3)
    # relu layers have exact zeros; tanh output does not track them
    assert float(jnp.mean(g_cap["h1"] == 0)) > 0.0
    # D: h0..h3 conv stages + final logit
    assert set(d_cap.keys()) == {"h0", "h1", "h2", "h3", "logit"}
    assert d_cap["logit"].shape == (4, 1)
    # capture must observe the very tensors the forward used (no recompute):
    # the tanh of the last captured pre-output equals the returned image
    np.testing.assert_array_equal(np.asarray(g_cap["h4"]), np.asarray(img))
