"""Unit tests for the op layer: shapes, init distributions, BN EMA semantics
(the reference had no tests at all — SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcgan_tpu.ops import (
    batch_norm_apply,
    batch_norm_init,
    conv2d_apply,
    conv2d_init,
    deconv2d_apply,
    deconv2d_init,
    linear_apply,
    linear_init,
    lrelu,
)


def test_linear_shapes_and_init():
    p = linear_init(jax.random.key(0), 100, 8192)
    assert p["w"].shape == (100, 8192)
    assert p["b"].shape == (8192,)
    # W ~ N(0, 0.02) (reference init, distriubted_model.py:165-166)
    assert abs(float(jnp.std(p["w"])) - 0.02) < 0.002
    assert float(jnp.max(jnp.abs(p["b"]))) == 0.0
    y = linear_apply(p, jnp.ones((4, 100)))
    assert y.shape == (4, 8192)


def test_conv2d_downsamples_by_stride():
    p = conv2d_init(jax.random.key(1), 3, 64)
    assert p["w"].shape == (5, 5, 3, 64)
    # truncated normal: no sample beyond 2 sigma
    assert float(jnp.max(jnp.abs(p["w"]))) <= 2 * 0.02 + 1e-6
    x = jnp.ones((2, 64, 64, 3))
    y = conv2d_apply(p, x)
    assert y.shape == (2, 32, 32, 64)


def test_deconv2d_upsamples_by_stride():
    p = deconv2d_init(jax.random.key(2), 512, 256)
    x = jnp.ones((2, 4, 4, 512))
    y = deconv2d_apply(p, x)
    assert y.shape == (2, 8, 8, 256)


def test_conv_deconv_bf16_compute_keeps_shapes():
    p = conv2d_init(jax.random.key(3), 3, 8)
    y = conv2d_apply(p, jnp.ones((1, 16, 16, 3)), compute_dtype=jnp.bfloat16)
    assert y.dtype == jnp.bfloat16 and y.shape == (1, 8, 8, 8)


def test_lrelu():
    x = jnp.array([-1.0, 0.0, 2.0])
    np.testing.assert_allclose(lrelu(x), [-0.2, 0.0, 2.0], rtol=1e-6)


class TestBatchNorm:
    def test_train_normalizes_batch(self):
        p, s = batch_norm_init(jax.random.key(0), 8)
        x = 5.0 + 3.0 * jax.random.normal(jax.random.key(1), (32, 4, 4, 8))
        y, _ = batch_norm_apply(p, s, x, train=True)
        # output moments ~ (0,1) modulated by scale/bias (scale ~ N(1,0.02))
        m = jnp.mean(y, axis=(0, 1, 2))
        v = jnp.var(y, axis=(0, 1, 2))
        np.testing.assert_allclose(np.asarray(m), np.asarray(p["bias"]),
                                   atol=1e-3)
        np.testing.assert_allclose(np.asarray(v),
                                   np.asarray(p["scale"]) ** 2, rtol=0.05)

    def test_ema_update_rule(self):
        """EMA: new = 0.9*old + 0.1*batch (momentum 0.9, the reference's
        ExponentialMovingAverage decay, distriubted_model.py:23)."""
        p, s = batch_norm_init(jax.random.key(0), 4)
        x = 2.0 + jax.random.normal(jax.random.key(1), (64, 8, 8, 4))
        _, s1 = batch_norm_apply(p, s, x, train=True, momentum=0.9)
        batch_mean = jnp.mean(x, axis=(0, 1, 2))
        expect = 0.9 * s["mean"] + 0.1 * batch_mean
        np.testing.assert_allclose(np.asarray(s1["mean"]), np.asarray(expect),
                                   rtol=1e-5)

    def test_eval_uses_running_stats(self):
        p, s = batch_norm_init(jax.random.key(0), 4)
        s = {"mean": jnp.full((4,), 2.0), "var": jnp.full((4,), 4.0)}
        x = jnp.full((2, 3, 3, 4), 2.0)
        y, s_out = batch_norm_apply(p, s, x, train=False)
        # (2-2)/2 * scale + bias = bias
        np.testing.assert_allclose(
            np.asarray(y[0, 0, 0]), np.asarray(p["bias"]), atol=1e-5)
        assert s_out is s  # eval must not mutate state

    def test_2d_input(self):
        """The reference special-cases 2-D inputs (moments over [0,1],
        distriubted_model.py:38-39); here 'all but channel' covers it."""
        p, s = batch_norm_init(jax.random.key(0), 16)
        x = jax.random.normal(jax.random.key(1), (64, 16))
        y, _ = batch_norm_apply(p, s, x, train=True)
        assert y.shape == (64, 16)
        np.testing.assert_allclose(np.asarray(jnp.mean(y, axis=0)),
                                   np.asarray(p["bias"]), atol=1e-3)

    def test_conditional_bn_per_class_affine(self):
        """cBN: each example is scaled/shifted by its class row; moments stay
        shared (SAGAN/BigGAN conditional BN)."""
        p, s = batch_norm_init(jax.random.key(0), 8, num_classes=3)
        assert p["scale"].shape == (3, 8) and p["bias"].shape == (3, 8)
        assert s["mean"].shape == (8,)  # moments are unconditional
        x = jax.random.normal(jax.random.key(1), (6, 4, 4, 8))
        labels = jnp.asarray([0, 1, 2, 0, 1, 2])
        y, s1 = batch_norm_apply(p, s, x, train=True, labels=labels)
        assert y.shape == x.shape
        # same input row, different class -> different output
        x2 = jnp.broadcast_to(x[:1], x.shape)
        y2, _ = batch_norm_apply(p, s, x2, train=True, labels=labels)
        assert np.abs(np.asarray(y2[0] - y2[1])).max() > 1e-4
        # class affine recovery: normalized x2 rows are identical, so
        # y2[i] = xhat * scale[label_i] + bias[label_i]
        xhat = (y2[0] - p["bias"][0]) / p["scale"][0]
        recon = xhat * p["scale"][1] + p["bias"][1]
        np.testing.assert_allclose(np.asarray(y2[1]), np.asarray(recon),
                                   atol=1e-4)

    def test_conditional_bn_requires_labels(self):
        p, s = batch_norm_init(jax.random.key(0), 8, num_classes=3)
        x = jax.random.normal(jax.random.key(1), (4, 2, 2, 8))
        with pytest.raises(ValueError, match="labels"):
            batch_norm_apply(p, s, x, train=True)

    def test_synced_moments_pmean(self):
        """Cross-replica BN: pmean'd moments under pmap equal global moments."""
        n = jax.local_device_count()
        p, s = batch_norm_init(jax.random.key(0), 4)
        x = jax.random.normal(jax.random.key(1), (n, 8, 2, 2, 4)) * 3.0 + 1.0

        def f(xs):
            y, s1 = batch_norm_apply(p, s, xs, train=True, axis_name="d")
            return y, s1

        _, s_sync = jax.pmap(f, axis_name="d")(x)
        global_mean = jnp.mean(x.reshape(-1, 4)[:, :], axis=0)
        expect = 0.9 * s["mean"] + 0.1 * global_mean
        # every replica must hold identical, globally-synced stats
        for i in range(n):
            np.testing.assert_allclose(np.asarray(s_sync["mean"][i]),
                                       np.asarray(expect), rtol=1e-4)
