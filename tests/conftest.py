"""Test env: 8 virtual CPU devices — the JAX-native "fake cluster" (SURVEY.md §4).

Must run before the first `import jax` anywhere in the test process.
"""

import os

# Force CPU: the ambient environment may pin JAX_PLATFORMS to a real TPU
# backend; tests must run on the virtual 8-device CPU mesh regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The ambient TPU plugin may have force-selected its own platform via
# jax.config.update("jax_platforms", ...) at interpreter startup, which beats
# the env var — override it back so tests never dial the real chip.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# Thread-discipline tripwire (ISSUE 8): the whole tier runs with the
# runtime collective-thread checks armed — every trainer/coordination/
# pipeline test doubles as a zero-trips proof at its knobs, and trainer
# SUBPROCESSES (chaos drill, bench pins) inherit the env var and arm
# themselves in train(). setdefault so DCGAN_THREAD_CHECKS=0 can switch
# it off for a bisection run.
os.environ.setdefault("DCGAN_THREAD_CHECKS", "1")

from dcgan_tpu.analysis import tripwire  # noqa: E402

tripwire.maybe_install()


def pytest_collection_modifyitems(config, items):
    """Two-tier suite (markers registered in pytest.ini): anything not
    explicitly marked `slow` is the smoke tier, so `-m smoke` and `-m slow`
    partition the suite exactly."""
    import pytest

    for item in items:
        if "slow" not in item.keywords:
            item.add_marker(pytest.mark.smoke)
