"""Self-attention + sequence-parallel ring attention (ops/attention.py).

The reference has no attention (pure-conv DCGAN, SURVEY.md §2.5); these tests
cover the framework's long-context machinery: exactness of the ring recurrence
against full attention (forward and gradients) on the 8-virtual-device mesh,
identity-at-init of the SAGAN block, model wiring at every legal attn_res, and
single-device-vs-sharded equivalence of the full train step with ring
attention under a spatial mesh.
"""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # see pytest.ini: excluded from the smoke tier
from jax.sharding import Mesh, PartitionSpec as P

from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
from dcgan_tpu.utils.backend import shard_map
from dcgan_tpu.models.dcgan import (
    discriminator_apply,
    gan_init,
    generator_apply,
)
from dcgan_tpu.ops.attention import (
    attn_apply,
    attn_init,
    full_attention,
    ring_attention,
)
from dcgan_tpu.parallel import make_mesh, make_parallel_train
from dcgan_tpu.train import make_train_step

ATTN_TINY = ModelConfig(output_size=16, gf_dim=8, df_dim=8, attn_res=8,
                        compute_dtype="float32")


def qkv(B=2, S=64, d=16, dv=32):
    k = jax.random.key(0)
    return tuple(
        jax.random.normal(jax.random.fold_in(k, i), (B, S, dim))
        for i, dim in enumerate((d, d, dv)))


def ring_mesh(n):
    return Mesh(np.asarray(jax.devices()).reshape(8 // n, n),
                ("data", "model"))


def max_abs_diff(a, b):
    d = jax.tree_util.tree_map(lambda x, y: float(jnp.max(jnp.abs(x - y))),
                               a, b)
    return max(jax.tree_util.tree_leaves(d))


class TestRingAttention:
    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_matches_full_attention(self, n):
        q, k, v = qkv()
        scale = q.shape[-1] ** -0.5
        full = full_attention(q, k, v, scale=scale)
        mesh = ring_mesh(n)
        spec = P(None, "model", None)
        ring = jax.jit(shard_map(
            functools.partial(ring_attention, axis_name="model", n_shards=n,
                              scale=scale),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec))(q, k, v)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(full),
                                   atol=2e-6)

    def test_gradients_match_full_attention(self):
        q, k, v = qkv()
        scale = q.shape[-1] ** -0.5
        mesh = ring_mesh(4)
        spec = P(None, "model", None)

        def loss_full(q, k, v):
            return jnp.sum(full_attention(q, k, v, scale=scale) ** 2)

        def loss_ring(q, k, v):
            f = shard_map(
                functools.partial(ring_attention, axis_name="model",
                                  n_shards=4, scale=scale),
                mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)
            return jnp.sum(f(q, k, v) ** 2)

        g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        for a, b in zip(g_full, g_ring):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=1e-5)

    def test_single_shard_degrades_to_full(self):
        q, k, v = qkv()
        scale = q.shape[-1] ** -0.5
        out = ring_attention(q, k, v, axis_name="model", n_shards=1,
                             scale=scale)
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(full_attention(q, k, v, scale=scale)))


class TestAttnBlock:
    def test_identity_at_init(self):
        # gamma starts at 0 (SAGAN residual gate): the block is a no-op until
        # training moves it, so inserting it cannot perturb reference
        # dynamics at step 0.
        params = attn_init(jax.random.key(0), 32)
        x = jax.random.normal(jax.random.key(1), (2, 8, 8, 32))
        np.testing.assert_array_equal(np.asarray(attn_apply(params, x)),
                                      np.asarray(x))

    def test_sagan_channel_plan(self):
        params = attn_init(jax.random.key(0), 64)
        assert params["query"]["w"].shape == (64, 8)
        assert params["key"]["w"].shape == (64, 8)
        assert params["value"]["w"].shape == (64, 32)
        assert params["out"]["w"].shape == (32, 64)
        assert params["gamma"].shape == ()

    def test_rejects_narrow_channels(self):
        with pytest.raises(ValueError, match=">= 8 channels"):
            attn_init(jax.random.key(0), 4)

    def test_ring_path_matches_dense_path(self):
        params = attn_init(jax.random.key(0), 16)
        # gamma = 0 makes both paths trivially equal; test with it live
        params = dict(params, gamma=jnp.asarray(0.7))
        x = jax.random.normal(jax.random.key(1), (4, 8, 8, 16))
        dense = attn_apply(params, x)
        ringy = attn_apply(params, x, seq_mesh=ring_mesh(4))
        np.testing.assert_allclose(np.asarray(ringy), np.asarray(dense),
                                   atol=1e-5)

    def test_rejects_unshardable_sequence(self):
        params = attn_init(jax.random.key(0), 16)
        x = jax.random.normal(jax.random.key(1), (2, 3, 3, 16))
        with pytest.raises(ValueError, match="does not shard"):
            attn_apply(params, x, seq_mesh=ring_mesh(8))

    def test_multihead_all_paths_agree(self):
        """Heads fold into the batch dim, so dense / flash / ring must stay
        mutually exact with num_heads > 1 (same params — the head count is an
        apply-time split)."""
        params = attn_init(jax.random.key(0), 32)
        params = dict(params, gamma=jnp.asarray(0.6))
        x = jax.random.normal(jax.random.key(1), (4, 8, 8, 32))
        dense = attn_apply(params, x, num_heads=2)
        ringy = attn_apply(params, x, num_heads=2, seq_mesh=ring_mesh(4))
        fused = attn_apply(params, x, num_heads=2, use_pallas=True)
        np.testing.assert_allclose(np.asarray(ringy), np.asarray(dense),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(dense),
                                   atol=1e-5)
        # heads=2 is a different function than heads=1
        single = attn_apply(params, x, num_heads=1)
        assert np.abs(np.asarray(dense) - np.asarray(single)).max() > 1e-4

    def test_multihead_rejects_indivisible(self):
        params = attn_init(jax.random.key(0), 16)  # qk dim 2, v dim 8
        x = jax.random.normal(jax.random.key(1), (2, 8, 8, 16))
        with pytest.raises(ValueError, match="does not divide"):
            attn_apply(params, x, num_heads=3)


class TestUlysses:
    """All-to-all sequence parallelism: the second SP strategy, exact vs the
    ring and the dense reference."""

    @pytest.mark.parametrize("n,heads", [(2, 2), (4, 4), (2, 4)])
    def test_matches_dense_and_ring(self, n, heads):
        params = attn_init(jax.random.key(0), 32)
        params = dict(params, gamma=jnp.asarray(0.8))
        x = jax.random.normal(jax.random.key(1), (4, 8, 8, 32))
        mesh = ring_mesh(n)
        dense = attn_apply(params, x, num_heads=heads)
        uly = attn_apply(params, x, num_heads=heads, seq_mesh=mesh,
                         seq_strategy="ulysses")
        ring = attn_apply(params, x, num_heads=heads, seq_mesh=mesh,
                          seq_strategy="ring")
        np.testing.assert_allclose(np.asarray(uly), np.asarray(dense),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(uly), np.asarray(ring),
                                   atol=1e-5)

    def test_gradients_match_dense(self):
        params = attn_init(jax.random.key(0), 32)
        params = dict(params, gamma=jnp.asarray(0.8))
        # batch must divide the mesh's data axis (8//2 = 4)
        x = jax.random.normal(jax.random.key(1), (4, 8, 8, 32))
        mesh = ring_mesh(2)

        def loss(fn_kwargs):
            def f(x):
                return jnp.sum(attn_apply(params, x, num_heads=2,
                                          **fn_kwargs) ** 2)
            return jax.grad(f)(x)

        g_dense = loss({})
        g_uly = loss({"seq_mesh": mesh, "seq_strategy": "ulysses"})
        np.testing.assert_allclose(np.asarray(g_uly), np.asarray(g_dense),
                                   atol=1e-4)

    def test_rejects_indivisible_heads(self):
        params = attn_init(jax.random.key(0), 32)
        x = jax.random.normal(jax.random.key(1), (4, 8, 8, 32))
        with pytest.raises(ValueError, match="divisible"):
            attn_apply(params, x, num_heads=1, seq_mesh=ring_mesh(2),
                       seq_strategy="ulysses")

    def test_unknown_strategy_rejected(self):
        params = attn_init(jax.random.key(0), 32)
        x = jax.random.normal(jax.random.key(1), (4, 8, 8, 32))
        with pytest.raises(ValueError, match="seq_strategy"):
            attn_apply(params, x, seq_mesh=ring_mesh(2),
                       seq_strategy="megatron")

    def test_sharded_train_step_ulysses(self):
        """Full train step under dp4 x sp2 with Ulysses attention matches the
        single-device step (same envelope as the ring test)."""
        # 16-ch attention site (gf=df=16) so the qk projection (ch/8 = 2)
        # splits into 2 heads; ATTN_TINY's 8-ch site gives qk dim 1
        cfg = TrainConfig(
            model=dataclasses.replace(ATTN_TINY, gf_dim=16, df_dim=16,
                                      attn_heads=2,
                                      attn_seq_strategy="ulysses"),
            batch_size=16, mesh=MeshConfig(data=4, model=2, spatial=True))
        xs = jnp.asarray(np.tanh(np.random.default_rng(0).normal(
            size=(16, 16, 16, 3))).astype(np.float32))
        key = jax.random.key(3)
        fns = make_train_step(cfg)
        s_ref, m_ref = jax.jit(fns.train_step)(
            fns.init(jax.random.key(0)), xs, key)
        pt = make_parallel_train(cfg)
        s_par, m_par = pt.step(pt.init(jax.random.key(0)), xs, key)
        np.testing.assert_allclose(float(m_par["d_loss"]),
                                   float(m_ref["d_loss"]), rtol=1e-4)
        np.testing.assert_allclose(float(m_par["g_loss"]),
                                   float(m_ref["g_loss"]), rtol=1e-4)
        assert max_abs_diff(jax.device_get(s_ref["params"]),
                            jax.device_get(s_par["params"])) \
            <= 2 * cfg.learning_rate + 1e-5


class TestModelWiring:
    def test_attn_res_validation(self):
        with pytest.raises(ValueError, match="not a feature-map resolution"):
            ModelConfig(output_size=64, attn_res=7)
        with pytest.raises(ValueError, match="not a feature-map resolution"):
            ModelConfig(output_size=64, attn_res=64)  # only intermediate maps
        ModelConfig(output_size=64, attn_res=4)       # base_size site is legal

    @pytest.mark.parametrize("attn_res", [4, 8])
    def test_generator_and_discriminator_run(self, attn_res):
        cfg = dataclasses.replace(ATTN_TINY, attn_res=attn_res)
        params, bn = gan_init(jax.random.key(0), cfg)
        assert "attn" in params["gen"] and "attn" in params["disc"]
        z = jax.random.uniform(jax.random.key(1), (4, cfg.z_dim),
                               minval=-1.0, maxval=1.0)
        img, _ = generator_apply(params["gen"], bn["gen"], z, cfg=cfg,
                                 train=True)
        assert img.shape == (4, 16, 16, 3)
        _, logit, _ = discriminator_apply(params["disc"], bn["disc"], img,
                                          cfg=cfg, train=True)
        assert logit.shape == (4, 1)

    def test_no_attn_params_without_attn_res(self):
        params, _ = gan_init(jax.random.key(0),
                             dataclasses.replace(ATTN_TINY, attn_res=0))
        assert "attn" not in params["gen"] and "attn" not in params["disc"]

    def test_gamma_learns(self):
        """One train step must move gamma off exactly 0 (gradient flows
        through the residual gate)."""
        cfg = TrainConfig(model=ATTN_TINY, batch_size=8,
                          mesh=MeshConfig(data=1))
        fns = make_train_step(cfg)
        state = fns.init(jax.random.key(0))
        xs = jnp.asarray(np.tanh(np.random.default_rng(0).normal(
            size=(8, 16, 16, 3))).astype(np.float32))
        state, metrics = jax.jit(fns.train_step)(state, xs, jax.random.key(1))
        assert float(state["params"]["disc"]["attn"]["gamma"]) != 0.0
        assert float(state["params"]["gen"]["attn"]["gamma"]) != 0.0
        for v in metrics.values():
            assert np.isfinite(float(v))


class TestShardedAttentionStep:
    def test_spatial_ring_step_matches_single_device(self):
        """dp4 x spatial2 with ring attention == the unsharded step (losses
        tight; params within the ±2·lr first-Adam-step sign-flip envelope —
        see test_parallel.py)."""
        cfg = TrainConfig(model=ATTN_TINY, batch_size=16,
                          mesh=MeshConfig(data=4, model=2, spatial=True))
        xs = jnp.asarray(np.tanh(np.random.default_rng(0).normal(
            size=(16, 16, 16, 3))).astype(np.float32))
        key = jax.random.key(3)

        fns = make_train_step(cfg)
        s_ref, m_ref = jax.jit(fns.train_step)(
            fns.init(jax.random.key(0)), xs, key)

        pt = make_parallel_train(cfg)
        s_par, m_par = pt.step(pt.init(jax.random.key(0)), xs, key)

        np.testing.assert_allclose(float(m_par["d_loss"]),
                                   float(m_ref["d_loss"]), rtol=1e-4)
        np.testing.assert_allclose(float(m_par["g_loss"]),
                                   float(m_ref["g_loss"]), rtol=1e-4)
        assert max_abs_diff(jax.device_get(s_ref["params"]),
                            jax.device_get(s_par["params"])) \
            <= 2 * cfg.learning_rate + 1e-5

    def test_dp_step_with_attention(self):
        """Pure DP (no spatial axis): attention stays dense and the batch
        shards; metrics finite across the mesh."""
        cfg = TrainConfig(model=ATTN_TINY, batch_size=16, mesh=MeshConfig())
        pt = make_parallel_train(cfg)
        xs = jnp.asarray(np.tanh(np.random.default_rng(0).normal(
            size=(16, 16, 16, 3))).astype(np.float32))
        state, metrics = pt.step(pt.init(jax.random.key(0)), xs,
                                 jax.random.key(1))
        assert int(state["step"]) == 1
        for v in metrics.values():
            assert np.isfinite(float(v))
