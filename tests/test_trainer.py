"""End-to-end trainer tests: CLI parsing, tiny synthetic run with sample grids
+ metrics + checkpointing, and resume-from-checkpoint (SURVEY.md §3.1/§3.3
call-stack parity)."""

import glob
import json
import os

import jax
import numpy as np
import pytest

from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
from dcgan_tpu.train.cli import build_parser, config_from_args
from dcgan_tpu.train.trainer import train


def tiny_cfg(tmp_path, **kw):
    base = dict(
        model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                          compute_dtype="float32"),
        batch_size=16,
        checkpoint_dir=str(tmp_path / "ckpt"),
        sample_dir=str(tmp_path / "samples"),
        sample_grid=(2, 2),
        sample_size=4,
        sample_every_steps=3,
        save_summaries_secs=0.0,   # every loop check fires
        save_model_secs=1e9,       # only the final forced save
        log_every_steps=0)
    base.update(kw)
    return TrainConfig(**base)


class TestCLI:
    def test_defaults_match_reference(self):
        args = build_parser().parse_args([])
        cfg = config_from_args(args)
        assert cfg.learning_rate == 2e-4 and cfg.beta1 == 0.5
        assert cfg.batch_size == 64 and cfg.max_steps == 1_200_000
        assert cfg.model.output_size == 64 and cfg.model.z_dim == 100
        assert cfg.save_summaries_secs == 10.0
        assert cfg.save_model_secs == 600.0

    def test_overrides(self):
        args = build_parser().parse_args(
            ["--output_size", "128", "--loss", "wgan-gp", "--mesh_model", "2",
             "--no_normalize", "--num_classes", "10"])
        cfg = config_from_args(args)
        assert cfg.model.output_size == 128 and cfg.model.num_up_layers == 5
        assert cfg.loss == "wgan-gp" and cfg.mesh.model == 2
        assert not cfg.normalize_inputs and cfg.model.num_classes == 10

    def test_bad_flag_rejected(self):
        with pytest.raises(SystemExit):
            build_parser().parse_args(["--loss", "lsgan"])

    def test_mesh_spatial_flag_reaches_config(self):
        args = build_parser().parse_args(["--mesh_model", "2",
                                          "--mesh_spatial"])
        cfg = config_from_args(args)
        assert cfg.mesh.spatial and cfg.mesh.model == 2

    def test_spatial_requires_model_axis(self):
        from dcgan_tpu.config import MeshConfig
        with pytest.raises(ValueError, match="model > 1"):
            MeshConfig(spatial=True)  # model defaults to 1 — silent no-op trap


@pytest.mark.slow
class TestTrainLoop:
    def test_synthetic_end_to_end(self, tmp_path):
        cfg = tiny_cfg(tmp_path, activation_summary_steps=5)
        state = train(cfg, synthetic_data=True, max_steps=7)
        assert int(jax.device_get(state["step"])) == 7
        # the held-out sample-loss probe (reference image_train.py:179-192)
        # fired at steps 3 and 6 and wrote sample/* scalars
        events = [json.loads(line) for line in
                  open(os.path.join(cfg.checkpoint_dir, "events.jsonl"))]
        sample_scalars = [e for e in events if e["kind"] == "scalars"
                          and "sample/d_loss" in e["values"]]
        assert {e["step"] for e in sample_scalars} == {3, 6}
        assert all(np.isfinite(e["values"]["sample/g_loss"])
                   for e in sample_scalars)

        # sample grids at steps 3 and 6 (2x2 of 8x8 images -> 32x32 PNG)
        grids = sorted(glob.glob(str(tmp_path / "samples" / "*.png")))
        assert [os.path.basename(g) for g in grids] == \
            ["train_00000003.png", "train_00000006.png"]
        from PIL import Image
        assert np.asarray(Image.open(grids[0])).shape == (32, 32, 3)

        # metric events written
        events = [json.loads(l) for l in
                  open(tmp_path / "ckpt" / "events.jsonl").read().splitlines()]
        kinds = {e["kind"] for e in events}
        assert "scalars" in kinds and "histograms" in kinds and "image" in kinds
        scalar_steps = [e["step"] for e in events if e["kind"] == "scalars"]
        assert scalar_steps[0] == 1

        # per-layer activation summaries at step 5 (_activation_summary parity)
        acts = [e for e in events if e["kind"] == "activations"]
        assert [e["step"] for e in acts] == [5]
        layers = acts[0]["values"]
        assert "gen/h0" in layers and "disc/h0" in layers \
            and "disc/logit" in layers
        # the reference's z / D(x) / D(G(z)) histogram channels
        # (image_train.py:86-89)
        assert {"z", "d_real_prob", "d_fake_prob"} <= set(layers)
        probs = layers["d_real_prob"]
        assert probs["bin_edges"][0] >= 0.0 and probs["bin_edges"][-1] <= 1.0
        rec = layers["gen/h0"]   # relu layer: sparsity in (0,1), 30-bin hist
        assert 0.0 < rec["zero_fraction"] < 1.0
        assert len(rec["bin_counts"]) == 30 \
            and len(rec["bin_edges"]) == 31
        assert sum(rec["bin_counts"]) == rec["count"]

        # final checkpoint exists at step 7
        from dcgan_tpu.utils.checkpoint import Checkpointer
        assert Checkpointer(cfg.checkpoint_dir).latest_step() == 7

    def test_sagan_recipe_end_to_end(self, tmp_path):
        """The full sagan64 recipe (attention + multi-head + spectral norm +
        hinge + TTUR + EMA) through the real trainer loop at tiny scale:
        checkpoints round-trip the attn params and sn_* state."""
        from dcgan_tpu.utils.checkpoint import Checkpointer

        cfg = tiny_cfg(
            tmp_path,
            model=ModelConfig(output_size=16, gf_dim=16, df_dim=16,
                              attn_res=8, attn_heads=2, spectral_norm="gd",
                              compute_dtype="float32"),
            loss="hinge", beta1=0.0,
            d_learning_rate=4e-4, g_learning_rate=1e-4, g_ema_decay=0.999,
            sample_every_steps=0)
        state = train(cfg, synthetic_data=True, max_steps=3)
        assert int(jax.device_get(state["step"])) == 3
        assert "attn" in state["params"]["gen"]
        assert any(k.startswith("sn_") for k in state["bn"]["disc"])
        # restore must reproduce the full tree, sn/attn leaves included
        from dcgan_tpu.parallel import make_mesh, make_parallel_train
        pt = make_parallel_train(cfg, make_mesh(cfg.mesh))
        restored = Checkpointer(cfg.checkpoint_dir).restore_latest(
            pt.init(jax.random.key(0)))
        assert restored is not None
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(restored["bn"]["disc"]["sn_conv0"])),
            np.asarray(jax.device_get(state["bn"]["disc"]["sn_conv0"])))

    def test_sample_pipeline_from_disk(self, tmp_path):
        """sample_image_dir present -> the probe's second pipeline reads it
        (reference image_train.py:84); absent -> probe skipped, not an
        error."""
        from dcgan_tpu.data import write_image_tfrecords
        from dcgan_tpu.parallel import make_mesh
        from dcgan_tpu.train.trainer import _sample_data_iterator

        cfg = tiny_cfg(tmp_path,
                       sample_image_dir=str(tmp_path / "sample_data"))
        mesh = make_mesh(cfg.mesh)
        assert _sample_data_iterator(cfg, mesh, synthetic=False) is None

        write_image_tfrecords(cfg.sample_image_dir, num_examples=32,
                              image_size=16, num_shards=1)
        it = _sample_data_iterator(cfg, mesh, synthetic=False)
        batch = next(it)
        assert batch.shape == (16, 16, 16, 3)

    def test_resume_from_checkpoint(self, tmp_path):
        cfg = tiny_cfg(tmp_path, sample_every_steps=0)
        train(cfg, synthetic_data=True, max_steps=4)
        # second invocation restores step 4 and continues to 6
        state = train(cfg, synthetic_data=True, max_steps=6)
        assert int(jax.device_get(state["step"])) == 6

    def test_resume_with_zero1_sharded_opt_state(self, tmp_path):
        """ZeRO-1 round-trip through Orbax: the data-sharded Adam moments
        save from and restore into their sharded layout."""
        cfg = tiny_cfg(tmp_path, sample_every_steps=0,
                       mesh=MeshConfig(shard_opt=True))
        train(cfg, synthetic_data=True, max_steps=2)
        state = train(cfg, synthetic_data=True, max_steps=4)
        assert int(jax.device_get(state["step"])) == 4
        # [0] is the grad-clip slot (EmptyState), [1] the adam chain
        mu_w = state["opt"]["disc"][1][0].mu["conv1"]["w"]
        full = int(np.prod(mu_w.shape))
        assert {int(np.prod(s.data.shape))
                for s in mu_w.addressable_shards} == {full // 8}

    def test_steps_per_call_scanned_dispatch(self, tmp_path):
        """steps_per_call=3 over 7 steps: two scanned calls + one aligned
        single step; cadence events still fire at the right steps and the
        final count is exact."""
        cfg = tiny_cfg(tmp_path, steps_per_call=3, sample_every_steps=3,
                       activation_summary_steps=6, nan_check_steps=3,
                       save_model_steps=999)
        state = train(cfg, synthetic_data=True, max_steps=7)
        assert int(jax.device_get(state["step"])) == 7
        events = [json.loads(line) for line in
                  open(os.path.join(cfg.checkpoint_dir, "events.jsonl"))]
        sample_steps = {e["step"] for e in events if e["kind"] == "scalars"
                        and "sample/d_loss" in e["values"]}
        assert sample_steps == {3, 6}
        assert {e["step"] for e in events if e["kind"] == "activations"} \
            == {6}

    def test_steps_per_call_cadence_validation(self, tmp_path):
        with pytest.raises(ValueError, match="multiple"):
            tiny_cfg(tmp_path, steps_per_call=4, sample_every_steps=3)

    def test_nan_check_aborts_with_context(self, tmp_path):
        """A NaN learning rate poisons D in the first update, so the G loss
        (computed against the updated D in sequential mode) is already NaN
        at step 1; the health gate must abort with step context instead of
        training garbage."""
        cfg = tiny_cfg(tmp_path, sample_every_steps=0,
                       learning_rate=float("nan"), nan_check_steps=1)
        with pytest.raises(FloatingPointError, match="step 1"):
            train(cfg, synthetic_data=True, max_steps=5)

    def test_conditional_loop(self, tmp_path):
        cfg = tiny_cfg(
            tmp_path,
            model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                              num_classes=4, compute_dtype="float32"),
            sample_every_steps=2)
        state = train(cfg, synthetic_data=True, max_steps=2)
        assert int(jax.device_get(state["step"])) == 2
        assert glob.glob(str(tmp_path / "samples" / "*.png"))

    def test_real_tfrecord_pipeline_end_to_end(self, tmp_path):
        """Full slice: shards on disk -> native loader -> sharded arrays ->
        sharded train step (the reference's worker call stack, SURVEY.md §3.1,
        minus the ps role)."""
        from dcgan_tpu.data.synthetic import write_image_tfrecords
        write_image_tfrecords(str(tmp_path / "data"), num_examples=64,
                              image_size=16, num_shards=2)
        cfg = tiny_cfg(tmp_path, data_dir=str(tmp_path / "data"),
                       shuffle_buffer=16, num_loader_threads=2,
                       sample_every_steps=0)
        state = train(cfg, max_steps=3)
        assert int(jax.device_get(state["step"])) == 3

    def test_manifest_record_dtype_adopted(self, tmp_path):
        """prepare now defaults to uint8 records while the trainer's
        record_dtype default stays float64 (reference parity) — the
        manifest's wire format must be adopted, same policy as evals, or
        the default prepare-then-train path fails its own manifest check."""
        import json

        from dcgan_tpu.data.synthetic import write_image_tfrecords
        write_image_tfrecords(str(tmp_path / "data"), num_examples=64,
                              image_size=16, num_shards=2,
                              record_dtype="uint8")
        # prepare.py writes the manifest; the synthetic test writer doesn't
        with open(tmp_path / "data" / "dataset.json", "w") as f:
            json.dump({"record_dtype": "uint8", "num_examples": 64,
                       "image_size": 16}, f)
        cfg = tiny_cfg(tmp_path, data_dir=str(tmp_path / "data"),
                       shuffle_buffer=16, num_loader_threads=2,
                       sample_every_steps=0)
        assert cfg.record_dtype == "float64"  # the mismatch being adopted
        state = train(cfg, max_steps=2)
        assert int(jax.device_get(state["step"])) == 2

    def test_conditional_real_labeled_tfrecords(self, tmp_path):
        """Conditional slice over labeled shards: int64 `label` feature ->
        native loader -> sharded (images, labels) -> conditional train step."""
        from dcgan_tpu.data.synthetic import write_image_tfrecords
        write_image_tfrecords(str(tmp_path / "data"), num_examples=64,
                              image_size=16, num_shards=2, num_classes=4)
        cfg = tiny_cfg(tmp_path,
                       model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                                         num_classes=4,
                                         compute_dtype="float32"),
                       data_dir=str(tmp_path / "data"),
                       shuffle_buffer=16, num_loader_threads=2,
                       sample_every_steps=0)
        state = train(cfg, max_steps=3)
        assert int(jax.device_get(state["step"])) == 3


class TestEpochSize:
    """Epoch counter derives from the dataset.json manifest when present
    (VERDICT r1 #8); the reference constant 107766*3 is the fallback
    (image_train.py:44)."""

    def test_manifest_num_examples_used(self, tmp_path):
        import json as _json

        from dcgan_tpu.train.trainer import _epoch_size

        (tmp_path / "dataset.json").write_text(
            _json.dumps({"num_examples": 50_000}))
        cfg = tiny_cfg(tmp_path, data_dir=str(tmp_path))
        assert _epoch_size(cfg) == 50_000

    def test_fallback_without_manifest(self, tmp_path):
        from dcgan_tpu.train.trainer import _epoch_size

        cfg = tiny_cfg(tmp_path, data_dir=str(tmp_path / "nope"))
        assert _epoch_size(cfg) == 323_298


@pytest.mark.slow
class TestGracefulShutdown:
    """SIGTERM mid-run -> checkpoint at the current step, clean exit, and a
    resumable directory (the TPU-preemption analogue of the reference
    Supervisor's crash recovery, image_train.py:123-141)."""

    def test_sigterm_checkpoints_and_resumes(self, tmp_path):
        import signal
        import subprocess
        import sys
        import time as _time

        code = f"""
import jax; jax.config.update("jax_platforms", "cpu")
from dcgan_tpu.config import ModelConfig, TrainConfig
from dcgan_tpu.train.trainer import train
cfg = TrainConfig(model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                                    compute_dtype="float32"),
                  batch_size=8, checkpoint_dir={str(tmp_path / "ck")!r},
                  sample_dir={str(tmp_path / "sm")!r},
                  sample_every_steps=0, save_summaries_secs=1e9,
                  save_model_secs=1e9, log_every_steps=1)
train(cfg, synthetic_data=True, max_steps=100000)
print("TRAIN_RETURNED", flush=True)
"""
        proc = subprocess.Popen(
            [sys.executable, "-c", code], cwd="/root/repo",
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        try:
            # wait until real steps are flowing, then signal
            saw_step = False
            deadline = _time.time() + 300
            for line in proc.stdout:
                if " step 3 " in line:
                    saw_step = True
                    proc.send_signal(signal.SIGTERM)
                    break
                if _time.time() > deadline:
                    break
            assert saw_step, "trainer never reached step 3"
            out = proc.stdout.read()
            rc = proc.wait(timeout=120)
            assert rc == 0, out
        finally:
            # never leak a 100000-step training child on any failure path
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        assert "received signal" in out and "TRAIN_RETURNED" in out

        from dcgan_tpu.utils.checkpoint import Checkpointer

        step = Checkpointer(str(tmp_path / "ck")).latest_step()
        assert step is not None and step >= 3

        # the directory resumes cleanly (config.json + mid-run checkpoint)
        from dcgan_tpu.config import load_config
        from dcgan_tpu.train.trainer import train as train_again

        cfg = load_config(str(tmp_path / "ck"))
        assert cfg is not None
        import dataclasses
        state = train_again(dataclasses.replace(cfg, log_every_steps=0),
                            synthetic_data=True, max_steps=step + 2)
        import numpy as np
        assert int(np.asarray(state["step"])) == step + 2


@pytest.mark.slow
class TestFidProbe:
    """In-training surrogate FID/KID probe (fid_every_steps > 0): eval/fid
    and eval/kid scalars land at the cadence, computed against the held-out
    sample stream."""

    def test_probe_writes_scalars(self, tmp_path):
        cfg = tiny_cfg(tmp_path, sample_every_steps=0, fid_every_steps=2,
                       fid_num_samples=64, save_summaries_secs=1e9)
        train(cfg, synthetic_data=True, max_steps=4)
        events = [json.loads(l) for l in
                  open(tmp_path / "ckpt" / "events.jsonl")]
        fids = {e["step"]: e["values"] for e in events
                if e["kind"] == "scalars" and "eval/fid" in e["values"]}
        assert set(fids) == {2, 4}
        for v in fids.values():
            assert np.isfinite(v["eval/fid"]) and v["eval/fid"] > 0
            assert np.isfinite(v["eval/kid"])

    def test_best_checkpoint_retained(self, tmp_path):
        """Improving probe scores snapshot into checkpoint_dir/best — the
        run ends holding both the latest and the best-FID state."""
        from dcgan_tpu.utils.checkpoint import Checkpointer

        cfg = tiny_cfg(tmp_path, sample_every_steps=0, fid_every_steps=2,
                       fid_num_samples=64, save_summaries_secs=1e9)
        train(cfg, synthetic_data=True, max_steps=4)
        best = Checkpointer(os.path.join(cfg.checkpoint_dir, "best"))
        step = best.latest_step()
        assert step in (2, 4)  # whichever probe scored best
        # and it restores like any checkpoint
        from dcgan_tpu.parallel import make_mesh, make_parallel_train

        pt = make_parallel_train(cfg, make_mesh(cfg.mesh))
        restored = best.restore_latest(pt.init(jax.random.key(0)))
        assert restored is not None
        assert int(jax.device_get(restored["step"])) == step

        # the score record exists and a resume re-seeds from it: a fresh
        # run in the same dir must NOT overwrite the best with its first
        # (worse-than-recorded) probe unless it actually improves
        score = json.load(open(os.path.join(cfg.checkpoint_dir, "best",
                                            "score.json")))
        assert score["step"] == step and np.isfinite(score["fid"])
        train(cfg, synthetic_data=True, max_steps=6)  # resume 2 more steps
        score2 = json.load(open(os.path.join(cfg.checkpoint_dir, "best",
                                             "score.json")))
        assert score2["fid"] <= score["fid"]  # never regresses

    def test_probe_multiprocess_needs_even_split(self, tmp_path,
                                                 monkeypatch):
        """The probe now RUNS under multihost (VERDICT r2 #5, the real
        2-process exercise is tests/test_multihost.py) — but the sample
        budget must divide evenly over the processes, validated at
        startup, not at the first probe step."""
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        cfg = tiny_cfg(tmp_path, fid_every_steps=2, fid_num_samples=65)
        with pytest.raises(ValueError, match="divide evenly"):
            train(cfg, synthetic_data=True, max_steps=2)
