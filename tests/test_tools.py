"""The measurement tools behind DESIGN.md §1b and the captures table.

What must hold: the spread/aggregation math the docs tables are rendered
from (tools/capture_all.py), the trainer-log parsing bench_trainer_loop's
throughput derivation rests on, and a CPU execution of the matmul-rate and
step-profile tools end to end (tiny shapes — the contract is "runs and
prints well-formed JSON", the numbers only mean anything on a chip).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.capture_all import (  # noqa: E402
    _best_bench_rows,
    _label_output_size,
    _mpx_cell,
    _render_roofline,
    _spread,
)


class TestSpread:
    def test_odd_even_and_single(self):
        assert _spread([3.0]) == {"n": 1, "median": 3.0, "min": 3.0,
                                  "max": 3.0}
        assert _spread([1.0, 9.0]) == {"n": 2, "median": 5.0, "min": 1.0,
                                       "max": 9.0}
        s = _spread([5.0, 1.0, 3.0])
        assert s["median"] == 3.0 and s["min"] == 1.0 and s["max"] == 5.0

    def test_best_rows_carry_spread(self):
        rows = [
            {"section": "matrix", "label": "a", "rc": 0, "date": "d1",
             "ms_per_step": 3.0,
             "parsed": [{"value": 10.0, "unit": "u", "vs_baseline": 5.0,
                         "metric": "m"}]},
            {"section": "matrix", "label": "a", "rc": 0, "date": "d2",
             "ms_per_step": 2.0,
             "parsed": [{"value": 20.0, "unit": "u", "vs_baseline": 10.0,
                         "metric": "m"}]},
            # failures and other sections must not count
            {"section": "matrix", "label": "a", "rc": 1, "date": "d3",
             "parsed": [{"value": 99.0}]},
            {"section": "fid", "label": "a", "rc": 0, "date": "d4",
             "parsed": [{"value": 77.0}]},
        ]
        best = _best_bench_rows(rows)
        a = best["a"]
        # best row's metadata comes from the winning capture
        assert a["value"] == 20.0 and a["ms"] == 2.0 and a["date"] == "d2"
        assert a["n"] == 2 and a["min"] == 10.0 and a["max"] == 20.0
        assert a["median"] == 15.0

    def test_best_rows_publish_highest_generation_only(self):
        """VERDICT r4 #1's contract: an attention label's best AND spread
        come from the highest kernel generation on record — a median over
        mixed generations describes no code that exists. Unstamped history
        is gen 0 (superseded once stamps appear)."""
        rows = [
            {"section": "matrix", "label": "attn", "rc": 0, "date": "d1",
             "parsed": [{"value": 3250.0}]},                  # pre-stamp
            {"section": "matrix", "label": "attn", "rc": 0, "date": "d2",
             "parsed": [{"value": 3260.0, "gen": 1}]},        # superseded
            {"section": "matrix", "label": "attn", "rc": 0, "date": "d3",
             "parsed": [{"value": 4050.0, "gen": 2}]},
            {"section": "matrix", "label": "attn", "rc": 0, "date": "d4",
             "parsed": [{"value": 4080.0, "gen": 2}]},
        ]
        a = _best_bench_rows(rows)["attn"]
        assert a["value"] == 4080.0 and a["gen"] == 2
        assert a["n"] == 2 and a["min"] == 4050.0  # gen<2 rows excluded

    def test_best_rows_preset_revision_default_is_one(self):
        """Unlisted presets ARE revision 1, so pre-stamp history of
        UNCHANGED presets must stay in the spread when a stamped rev-1
        capture arrives — only history behind an explicit bump retires
        (advisor r5 fix: a default of 0 silently discarded every unchanged
        preset's history on the first stamped harvest)."""
        rows = [
            {"section": "matrix", "label": "p", "rc": 0, "date": "d1",
             "parsed": [{"value": 100.0}]},                   # pre-stamp
            {"section": "matrix", "label": "p", "rc": 0, "date": "d2",
             "parsed": [{"value": 110.0, "rev": 1}]},         # same config
        ]
        p = _best_bench_rows(rows)["p"]
        assert p["n"] == 2 and p["min"] == 100.0 and p["value"] == 110.0
        # an explicit bump DOES retire older rows
        rows.append({"section": "matrix", "label": "p", "rc": 0,
                     "date": "d3", "parsed": [{"value": 90.0, "rev": 2}]})
        p = _best_bench_rows(rows)["p"]
        assert p["n"] == 1 and p["value"] == 90.0 and p["rev"] == 2

    def test_roofline_render(self):
        rows = [
            {"section": "roofline", "label": "matmul-rate", "rc": 0,
             "date": "d1", "parsed": [
                 # pre-K capture (square chain): treated as K = N
                 {"form": "matmul", "m": 8, "n": 8, "tflops": 1.0,
                  "ms_per_matmul": 0.5},
                 {"form": "matmul", "m": 8, "k": 8, "n": 8, "tflops": 2.0,
                  "ms_per_matmul": 0.25}]},  # best per shape wins
            {"section": "roofline", "label": "step-profile", "rc": 0,
             "date": "d1", "parsed": [
                 {"label": "step-profile", "batch": 64, "scan": 50,
                  "step_ms": 3.0, "fwd_ms": 2.0, "bwd_opt_ms_derived": 1.0,
                  "g_forward_ms": 1.5, "adam_ms": 1.2,
                  "flops_per_step": 192e9, "bytes_accessed": 2.3e9,
                  "tflops_effective": 64.0, "hbm_gbps_effective": 766.0}]},
            {"section": "roofline", "label": "trainer-loop", "rc": 0,
             "date": "d1", "parsed": [
                 {"label": "trainer-loop", "images_per_sec_chip": 19000.0,
                  "ms_per_step": 3.3, "steps_per_call": 50}]},
            # a failed roofline row contributes nothing
            {"section": "roofline", "label": "trainer-loop", "rc": 1,
             "date": "d2", "parsed": [
                 {"label": "trainer-loop", "images_per_sec_chip": 9e9}]},
        ]
        text = "\n".join(_render_roofline(rows))
        assert "| 8×8×8 | 2.0 | 0.25 |" in text   # best-per-shape
        assert "192.0 GFLOP" in text
        assert "19000 img/s/chip" in text
        assert "9000000000" not in text

    def test_roofline_render_empty(self):
        assert _render_roofline([]) == []

    def test_label_output_size_and_mpx(self):
        """The Mpx/s column's resolution join (VERDICT Weak #2): presets
        resolve through the registry, family tokens by their trailing
        digits, and the b<batch>/attn<res> knob tokens must NEVER be read
        as resolutions."""
        assert _label_output_size("wgan-gp") == 64          # preset lookup
        assert _label_output_size("dcgan64-b256") == 64     # b256 is batch
        assert _label_output_size("dcgan256-attn128-flash") == 256
        assert _label_output_size("sngan-cifar10") == 32
        assert _label_output_size("unknowable") is None
        assert _mpx_cell("dcgan256-attn128-flash", 48.9) == "3.2"
        assert _mpx_cell("dcgan64-headline", 20000.0) == "81.9"
        assert _mpx_cell("unknowable", 100.0) == "—"

    def test_per_family_scan_annotation(self):
        """VERDICT Weak #6: a scanning family's roofline row must either
        carry the trip-exact stamp (new captures) or flag the counted-once
        undercount (pre-fix captures) — never republish the bad FLOP count
        bare."""
        def profile_row(**kw):
            base = {"label": "step-profile", "preset": "wgan-gp",
                    "batch": 64, "scan": 50, "step_ms": 2.85,
                    "fwd_ms": 1.36, "bwd_opt_ms_derived": 1.49,
                    "g_forward_ms": 1.0, "adam_ms": 1.0,
                    "flops_per_step": 279.6e9, "bytes_accessed": 2.85e9,
                    "tflops_effective": 20.6, "hbm_gbps_effective": 225.0}
            base.update(kw)
            return {"section": "roofline", "label": "step-profile",
                    "rc": 0, "date": "d1", "parsed": [base]}

        old = "\n".join(_render_roofline([profile_row()]))
        assert "wgan-gp (scanned ×5)\\*" in old
        assert "count the ×5 scan body once" in old
        new = "\n".join(_render_roofline(
            [profile_row(scan_trips={"n_critic": 5})]))
        assert "scanned ×5, trip-exact" in new
        assert "body once" not in new

    def test_render_docs_end_to_end(self, tmp_path, monkeypatch):
        """render_docs over a synthetic captures log into temp docs: every
        fid-trajectory label renders its own table (a latest-run-wins
        render would let one ladder evict the other), and loader spreads
        group per wire format (pooling float64 and uint8 into one min-max
        would fabricate a range no format has)."""
        import tools.capture_all as ca

        rows = [
            {"section": "fid", "label": "long", "rc": 0, "date": "d1",
             "cmd": "c1", "parsed": [{"step": 0, "fid": 0.5},
                                     {"monotonic": True,
                                      "spearman_steps_vs_fid": -1.0,
                                      "snapshots": 1}]},
            {"section": "fid", "label": "early", "rc": 0, "date": "d2",
             "cmd": "c2", "parsed": [{"step": 0, "fid": 0.4}]},
            {"section": "fid", "label": "long", "rc": 0, "date": "d3",
             "cmd": "c3", "parsed": [{"step": 0, "fid": 0.3}]},
            {"section": "loader", "label": "loader-ceiling", "rc": 0,
             "date": "d1", "cmd": "c", "parsed": [
                 {"images_per_sec": 15000.0, "record_dtype": "float64",
                  "threads": 16}]},
            {"section": "loader", "label": "loader-ceiling-uint8", "rc": 0,
             "date": "d1", "cmd": "c", "parsed": [
                 {"images_per_sec": 27000.0, "record_dtype": "uint8",
                  "threads": 16}]},
        ]
        captures = tmp_path / "captures.jsonl"
        captures.write_text("".join(json.dumps(r) + "\n" for r in rows))
        baseline = tmp_path / "B.md"
        design = tmp_path / "D.md"
        baseline.write_text("# B\n")
        design.write_text("# D\n")
        monkeypatch.setattr(ca, "CAPTURES", str(captures))
        monkeypatch.setattr(ca, "BASELINE_MD", str(baseline))
        monkeypatch.setattr(ca, "DESIGN_MD", str(design))
        ca.render_docs()
        text = baseline.read_text()
        assert "Chip FID/KID trajectory (long" in text
        assert "Chip FID/KID trajectory (early" in text
        assert "`c3`" in text and "`c1`" not in text  # latest long run wins
        assert "- float64: best 15000 img/s" in text
        assert "- uint8: best 27000 img/s" in text
        # spreads are per-format: no pooled 15000-27000 range anywhere
        assert "15000–27000" not in text


class TestTraceSummary:
    def test_committed_chip_trace_parses(self):
        """The committed v5e trace artifact must keep yielding the step-time
        evidence DESIGN.md §1b cites: 5 per-step train_step executions at
        ~2.845 ms on the device's own timeline (now through the shared
        dcgan_tpu/utils/trace.py parser — satellite reroute)."""
        from tools.trace_summary import find_trace, summarize

        rows = summarize(find_trace(os.path.join(
            REPO, "docs", "assets", "trace_train_step_v5e.json.gz")))
        step = next(r for r in rows if "train_step" in r["program"])
        assert step["n"] == 5
        assert 2.8 < step["ms_min"] <= step["ms_max"] < 2.9

    def test_find_trace_dir_and_missing(self, tmp_path):
        from tools.trace_summary import find_trace

        with pytest.raises(FileNotFoundError):
            find_trace(str(tmp_path))
        d = tmp_path / "plugins" / "profile" / "x"
        d.mkdir(parents=True)
        p = d / "vm.trace.json.gz"
        p.write_bytes(b"")
        assert find_trace(str(tmp_path)) == str(p)

    def _write_trace(self, path, events):
        import gzip

        with gzip.open(str(path), "wt") as f:
            json.dump({"traceEvents": events}, f)
        return str(path)

    def test_cpu_capture_falls_back_instead_of_printing_nothing(
            self, tmp_path):
        """Satellite fix: a no-TPU capture used to print NOTHING and exit
        0 — now it reports the busiest fallback track with a stderr note."""
        path = self._write_trace(tmp_path / "c.trace.json.gz", [
            {"ph": "M", "pid": 7, "name": "process_name",
             "args": {"name": "/host:CPU"}},
            {"ph": "M", "pid": 7, "tid": 2, "name": "thread_name",
             "args": {"name": "tf_XLATfrtCpuClient/1"}},
            {"ph": "X", "pid": 7, "tid": 2, "name": "dot.1",
             "ts": 0, "dur": 500}])
        res = subprocess.run(
            [sys.executable, "tools/trace_summary.py", path], cwd=REPO,
            capture_output=True, text=True, timeout=120)
        assert res.returncode == 0, res.stderr
        rows = [json.loads(l) for l in res.stdout.splitlines()]
        assert rows and rows[0]["program"] == "dot.1"
        assert "no TPU-named process" in res.stderr

    def test_no_device_events_exits_nonzero_with_hint(self, tmp_path):
        path = self._write_trace(tmp_path / "e.trace.json.gz", [
            {"ph": "M", "pid": 7, "name": "process_name",
             "args": {"name": "/host:CPU"}}])
        res = subprocess.run(
            [sys.executable, "tools/trace_summary.py", path], cwd=REPO,
            capture_output=True, text=True, timeout=120)
        assert res.returncode == 1
        assert res.stdout.strip() == ""
        assert "no duration events" in res.stderr
        assert "--profile_dir" in res.stderr  # the usage hint

    def test_committed_chip_trace_digest(self):
        """The v5e artifact is also the DIGEST regression fixture (ISSUE 6
        satellite): device attribution over the capture — ~14.25 ms busy
        across 5 steps, the rest idle between dispatches."""
        from dcgan_tpu.utils.trace import digest

        d = digest(os.path.join(REPO, "docs", "assets",
                                "trace_train_step_v5e.json.gz"))
        assert d["source"] == "tpu"
        assert 2.8 < d["program_ms_median"] < 2.9  # devstep_ms source
        assert 14.0 < d["compute_ms"] < 15.0
        assert 40.0 < d["idle_gap_ms"] < 50.0
        assert d["collective_ms"] == 0.0


class TestTrainerLoopParsing:
    def test_log_regex_and_window(self):
        from tools.bench_trainer_loop import LOG_RE

        out = ("[dcgan_tpu] epoch 0 step 500 time 30.0s d_loss 1.0 "
               "g_loss 1.0\n"
               "[dcgan_tpu] epoch 0 step 1000 time 33.2s d_loss 1.0 "
               "g_loss 1.0\n"
               "[dcgan_tpu] epoch 1 step 5000 time 46.0s d_loss 1.0 "
               "g_loss 1.0\n")
        pts = [(int(m.group(1)), float(m.group(2)))
               for m in LOG_RE.finditer(out)]
        assert pts == [(500, 30.0), (1000, 33.2), (5000, 46.0)]


class TestAnalysisAllSmoke:
    """THE consolidated analyzer pin (ISSUE 14, replacing the separate
    AST + semantic subprocess pins): ONE `python -m dcgan_tpu.analysis
    --all` subprocess must run every tier CLEAN — zero non-baselined
    findings across DCG001-015 — AND regenerate BOTH committed contracts
    (analysis/programs.lock.jsonl, analysis/protocol.lock.jsonl)
    byte-identically. `--write-manifest/--write-lock <tmp>` recompute
    every row (exit code still gated on the non-drift findings), and the
    byte compares against the committed files ARE the drift checks at
    full strength. The CLI arranges its own canonical topology (CPU, 2
    virtual devices) before jax initializes, so the pin is
    environment-stable. Per-tier flags keep working and are covered
    in-process (tests/test_analysis.py, tests/test_protocol.py) plus the
    dedicated --protocol subprocess pin below."""

    def test_all_tiers_clean_and_locks_reproducible_within_budget(
            self, tmp_path):
        import time

        committed_manifest = os.path.join(
            REPO, "dcgan_tpu", "analysis", "programs.lock.jsonl")
        committed_lock = os.path.join(
            REPO, "dcgan_tpu", "analysis", "protocol.lock.jsonl")
        out_manifest = str(tmp_path / "programs.lock.jsonl")
        out_lock = str(tmp_path / "protocol.lock.jsonl")
        t0 = time.monotonic()
        res = subprocess.run(
            [sys.executable, "-m", "dcgan_tpu.analysis", "--all",
             "--json", "--write-manifest", out_manifest,
             "--write-lock", out_lock],
            cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=510)
        elapsed = time.monotonic() - t0
        assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-800:])
        summary = json.loads(
            [l for l in res.stdout.splitlines()
             if l.startswith("{")][-1])
        assert summary["label"] == "dcgan-analysis-all"
        assert summary["new_findings"] == 0
        tiers = summary["tiers"]
        # per-tier timing is part of the contract: a tier that silently
        # stopped running would report no timing row
        assert set(tiers) == {"ast", "semantic", "protocol"}
        assert all(t["ms"] > 0 for t in tiers.values())
        assert tiers["ast"]["files"] > 50
        assert tiers["semantic"]["programs"] > 60
        # the protocol lattice really explored (ISSUE 14 acceptance:
        # >= 4 configs x >= 6 interleavings); the stderr line makes
        # silent shrinkage visible in CI logs
        assert tiers["protocol"]["configs"] >= 4
        assert tiers["protocol"]["interleavings"] >= 24
        assert "explored" in res.stderr and "interleaving" in res.stderr
        for out, committed, what in (
                (out_manifest, committed_manifest, "programs.lock.jsonl"),
                (out_lock, committed_lock, "protocol.lock.jsonl")):
            with open(out, "rb") as f_new, open(committed, "rb") as f_old:
                assert f_new.read() == f_old.read(), (
                    f"regenerated {what} differs from the committed file "
                    "— either the contract drifted (regenerate "
                    "deliberately and review the diff) or determinism "
                    "broke")
        # The budget keeps the tier-1 pin from quietly eating the tier.
        # Recalibrated as the tiers grew (the semantic tier compiles
        # every dispatchable program: 70 -> 97 manifest rows across the
        # pallas/precision, progressive, and live-elastic PRs, then
        # 97 -> 124 with the collective-overlap variants; the protocol
        # lattice is 129 interleavings with the serving-fleet
        # promotion-drain configs): measured ~380 s quiet / 444 s under
        # contention on a 1-core host at 124 rows, where ~370 s quiet
        # was the 97-row measurement and the original 300 s bound — set
        # when the tier took ~65 s on 2 cores — already failed BEFORE
        # the live-elastic rows landed (339 s at that commit on the
        # same host).
        assert elapsed < 530, f"--all took {elapsed:.0f}s"


class TestProtocolAnalysisSmoke:
    """ISSUE 14's dedicated tier pin: `--protocol --json` alone must run
    clean inside a tight budget (the simulator is pure host code — if it
    slows down, its lattice grew in a way someone should look at), and
    must PRINT the explored-interleaving counts so lattice shrinkage can
    never be silent in logs."""

    def test_protocol_clean_within_budget(self):
        import time

        t0 = time.monotonic()
        res = subprocess.run(
            [sys.executable, "-m", "dcgan_tpu.analysis", "--protocol",
             "--json"],
            cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=180)
        elapsed = time.monotonic() - t0
        assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-800:])
        summary = json.loads(
            [l for l in res.stdout.splitlines()
             if l.startswith("{")][-1])
        assert summary["label"] == "dcgan-analysis-protocol"
        assert summary["new_findings"] == 0
        assert summary["configs"] >= 4
        assert summary["interleavings"] >= 24
        import re as _re

        m = _re.search(r"explored (\d+) interleaving\(s\) across (\d+) "
                       r"knob config\(s\)", res.stderr)
        assert m, f"no interleaving-count line in stderr: {res.stderr}"
        assert int(m.group(1)) == summary["interleavings"]
        assert elapsed < 120, f"protocol tier took {elapsed:.0f}s"


@pytest.mark.chaos
class TestChaosDrillSmoke:
    """tools/chaos_drill.py --smoke pinned into tier-1 (not slow, per the
    chaos-marker contract in pytest.ini): the cheap scenario subset —
    corrupt-record quarantine, transient-IO retry, services-crash
    surfacing — must keep passing end to end through real trainer
    subprocesses. The full 9-scenario matrix (rollback + checkpoint
    fallback + the ISSUE 6 observability trio included) runs standalone:
    `python tools/chaos_drill.py`."""

    def test_smoke_matrix_passes(self):
        res = subprocess.run(
            [sys.executable, "tools/chaos_drill.py", "--smoke"], cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=600)
        lines = [json.loads(l) for l in res.stdout.splitlines()
                 if l.startswith("{")]
        summary = lines[-1]
        assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-500:])
        assert summary["label"] == "chaos-drill"
        assert summary["scenarios"] == 3 and summary["failed"] == 0
        scenarios = {p["scenario"]: p for p in lines if "scenario" in p}
        assert set(scenarios) == {"corrupt-record", "io-error-once",
                                  "services-crash"}
        assert scenarios["corrupt-record"]["corrupt_records"] >= 1

    def test_multihost_smoke_passes_within_budget(self):
        """tools/chaos_drill.py --multihost --smoke pinned into tier-1
        (ISSUE 4): the cheapest coordinated-recovery scenario — SIGTERM on
        one host of a real 2-process localhost-gRPC job becomes a
        collective stop + bit-exact resume — with an explicit runtime
        budget so the pin can never quietly eat the tier. The full
        3-scenario matrix (coordinated rollback + watchdog trip included)
        runs standalone: `python tools/chaos_drill.py --multihost`."""
        import time

        t0 = time.monotonic()
        res = subprocess.run(
            [sys.executable, "tools/chaos_drill.py", "--multihost",
             "--smoke"], cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=420)
        elapsed = time.monotonic() - t0
        lines = [json.loads(l) for l in res.stdout.splitlines()
                 if l.startswith("{")]
        summary = lines[-1]
        assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-500:])
        assert summary["label"] == "chaos-drill-multihost"
        assert summary["scenarios"] == 1 and summary["failed"] == 0
        scenarios = {p["scenario"]: p for p in lines if "scenario" in p}
        assert set(scenarios) == {"mh-sigterm-stop"}
        assert scenarios["mh-sigterm-stop"]["resumed"] is True
        # runtime budget: two tiny 2-process launches; 300 s is ~4x the
        # measured cost on a quiet host, headroom for CI contention
        assert elapsed < 300, f"multihost smoke took {elapsed:.0f}s"


@pytest.mark.chaos
class TestObservabilitySmoke:
    """ISSUE 6's tier-1 pin (chaos-marker pattern from PRs 3-5): the
    trigger-file capture -> in-process digest loop and the flight-recorder
    dump triggers must keep working end to end through real trainer
    subprocesses, inside an explicit runtime budget. The full matrix runs
    standalone: `JAX_PLATFORMS=cpu python tools/chaos_drill.py`."""

    def test_trace_trigger_and_flight_recorder_within_budget(self):
        import time

        t0 = time.monotonic()
        res = subprocess.run(
            [sys.executable, "tools/chaos_drill.py", "--only",
             "flight-recorder", "watchdog-dump", "trace-trigger"],
            cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=420)
        elapsed = time.monotonic() - t0
        lines = [json.loads(l) for l in res.stdout.splitlines()
                 if l.startswith("{")]
        summary = lines[-1]
        assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-500:])
        assert summary["scenarios"] == 3 and summary["failed"] == 0
        scenarios = {p["scenario"]: p for p in lines if "scenario" in p}
        assert set(scenarios) == {"flight-recorder", "watchdog-dump",
                                  "trace-trigger"}
        assert scenarios["flight-recorder"]["failing_step"] == 3
        assert scenarios["watchdog-dump"]["phase"] == "step-dispatch"
        assert scenarios["trace-trigger"]["device_compute_ms"] > 0
        # three tiny trainer subprocesses (~15 s each on a quiet host,
        # compile-dominated); ~4x headroom for CI contention
        assert elapsed < 300, f"observability smoke took {elapsed:.0f}s"


@pytest.mark.chaos
class TestPipelineRollbackSmoke:
    """ISSUE 7's tier-1 pin (chaos-marker pattern): a NaN fault under
    --pipeline_gd must drain the in-flight fake stack at the rollback,
    refill from the restored state, complete, and replay bit-exactly —
    through real trainer subprocesses, inside an explicit runtime budget.
    The full matrix runs standalone:
    `JAX_PLATFORMS=cpu python tools/chaos_drill.py`."""

    def test_pipeline_rollback_within_budget(self):
        import time

        t0 = time.monotonic()
        res = subprocess.run(
            [sys.executable, "tools/chaos_drill.py", "--only",
             "pipeline-rollback"],
            cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=420)
        elapsed = time.monotonic() - t0
        lines = [json.loads(l) for l in res.stdout.splitlines()
                 if l.startswith("{")]
        summary = lines[-1]
        assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-500:])
        assert summary["scenarios"] == 1 and summary["failed"] == 0
        scenarios = {p["scenario"]: p for p in lines if "scenario" in p}
        assert set(scenarios) == {"pipeline-rollback"}
        assert scenarios["pipeline-rollback"]["rollbacks"] >= 1
        assert scenarios["pipeline-rollback"]["replay_bit_exact"] is True
        # two tiny trainer subprocesses (the replay pair, ~20 s each on a
        # quiet host, compile-dominated); ~4x headroom for CI contention
        assert elapsed < 300, f"pipeline-rollback smoke took {elapsed:.0f}s"


@pytest.mark.chaos
class TestProgressiveSwitchSmoke:
    """ISSUE 15's tier-1 pin (chaos-marker pattern): a NaN at the first
    step after a progressive phase switch must roll back to the
    POST-switch snapshot (the new phase's tree), complete, replay
    STATE_SUM bit-exactly, and keep the pre-switch phase's losses
    bit-exact against an unfaulted control — through real trainer
    subprocesses, inside an explicit runtime budget. The full matrix
    runs standalone: `JAX_PLATFORMS=cpu python tools/chaos_drill.py`."""

    def test_progressive_switch_within_budget(self):
        import time

        t0 = time.monotonic()
        res = subprocess.run(
            [sys.executable, "tools/chaos_drill.py", "--only",
             "progressive-switch"],
            cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=420)
        elapsed = time.monotonic() - t0
        lines = [json.loads(l) for l in res.stdout.splitlines()
                 if l.startswith("{")]
        summary = lines[-1]
        assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-500:])
        assert summary["scenarios"] == 1 and summary["failed"] == 0
        scenarios = {p["scenario"]: p for p in lines if "scenario" in p}
        assert set(scenarios) == {"progressive-switch"}
        row = scenarios["progressive-switch"]
        assert row["rollbacks"] >= 1
        assert row["replay_bit_exact"] is True
        assert row["preswitch_losses_bit_exact"] is True
        # three tiny trainer subprocesses (faulted pair + control, each
        # compiling two phase surfaces); ~4x headroom for CI contention
        assert elapsed < 300, f"progressive-switch smoke took {elapsed:.0f}s"


@pytest.mark.chaos
class TestZeroRollbackSmoke:
    """ISSUE 13's tier-1 pin (chaos-marker pattern): a NaN fault under
    --zero_stage 3 must restore the data-SHARDED state from the rollback
    snapshot, complete, and replay losses + STATE_SUM bit-exactly against
    a --zero_stage 1 control — through real trainer subprocesses, inside
    an explicit runtime budget. The full matrix runs standalone:
    `JAX_PLATFORMS=cpu python tools/chaos_drill.py`."""

    def test_zero_rollback_within_budget(self):
        import time

        t0 = time.monotonic()
        res = subprocess.run(
            [sys.executable, "tools/chaos_drill.py", "--only",
             "zero-rollback"],
            cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=420)
        elapsed = time.monotonic() - t0
        lines = [json.loads(l) for l in res.stdout.splitlines()
                 if l.startswith("{")]
        summary = lines[-1]
        assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-500:])
        assert summary["scenarios"] == 1 and summary["failed"] == 0
        scenarios = {p["scenario"]: p for p in lines if "scenario" in p}
        assert set(scenarios) == {"zero-rollback"}
        assert scenarios["zero-rollback"]["rollbacks"] >= 1
        assert scenarios["zero-rollback"]["replay_bit_exact"] is True
        # two tiny 2-device trainer subprocesses (~25 s each on a quiet
        # host, compile-dominated); ~4x headroom for CI contention
        assert elapsed < 300, f"zero-rollback smoke took {elapsed:.0f}s"


@pytest.mark.chaos
class TestElasticShrinkSmoke:
    """ISSUE 12's tier-1 pin (chaos-marker pattern): a checkpoint saved
    by 2 processes must resume on 1 process (2 virtual devices — same
    2-way data mesh, different process census) through the sharding
    sidecar's host-staged reshard, with post-resume losses and final
    STATE_SUM replaying against a same-topology control resume to within
    ulp-scale reduction-order tolerances (the cross-process collective
    may sum partials in a different order than the intra-process one —
    the drill documents the bound; see chaos_drill._elastic_scenario) —
    through real trainer subprocesses, inside an explicit runtime budget.
    The grow direction (and the rest of the matrix) runs standalone:
    `JAX_PLATFORMS=cpu python tools/chaos_drill.py`."""

    def test_elastic_shrink_within_budget(self):
        import time

        t0 = time.monotonic()
        res = subprocess.run(
            [sys.executable, "tools/chaos_drill.py", "--only",
             "elastic-shrink"],
            cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=420)
        elapsed = time.monotonic() - t0
        lines = [json.loads(l) for l in res.stdout.splitlines()
                 if l.startswith("{")]
        summary = lines[-1]
        assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-500:])
        assert summary["scenarios"] == 1 and summary["failed"] == 0
        scenarios = {p["scenario"]: p for p in lines if "scenario" in p}
        assert set(scenarios) == {"elastic-shrink"}
        row = scenarios["elastic-shrink"]
        assert row["direction"] == "2proc->1proc"
        assert row["replay_within_tolerance"] is True
        assert row["state_sum_rel"] <= 5e-4
        assert row["final_step"] == 6
        assert row["reshard_ms"] > 0
        # five tiny trainer launches (one 2-proc save pair, a 1-proc
        # cross resume, a 2-proc control pair; ~20 s measured total on a
        # quiet host) — generous headroom for CI contention
        assert elapsed < 300, f"elastic-shrink smoke took {elapsed:.0f}s"


@pytest.mark.chaos
class TestLiveNoticeShrinkSmoke:
    """ISSUE 18's tier-1 pin: a chaos preemption notice at step 3 drives
    a LIVE t2x1 -> t1x1 mesh switch in one uninterrupted trainer process
    (no restart), the run completes to step 6, the switch line reports
    compile_requests_delta=0 (both topologies AOT-warmed+primed up
    front), pre-notice losses replay bit-exactly against an
    armed-but-unnotified control, and elastic/live_* event keys appear
    only in the notified run. The grow-back direction runs standalone:
    `JAX_PLATFORMS=cpu python tools/chaos_drill.py --only grow-back`."""

    def test_live_notice_shrink_within_budget(self):
        import time

        t0 = time.monotonic()
        res = subprocess.run(
            [sys.executable, "tools/chaos_drill.py", "--only",
             "notice-shrink"],
            cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=420)
        elapsed = time.monotonic() - t0
        lines = [json.loads(l) for l in res.stdout.splitlines()
                 if l.startswith("{")]
        summary = lines[-1]
        assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-500:])
        assert summary["scenarios"] == 1 and summary["failed"] == 0
        scenarios = {p["scenario"]: p for p in lines if "scenario" in p}
        row = scenarios["notice-shrink"]
        assert row["compile_requests_delta"] == 0
        assert row["final_step"] == 6
        assert row["switch_ms"] > 0
        assert row["state_sum_rel"] <= 5e-4
        # two tiny 2-device trainer launches (control + notified, ~25 s
        # measured total on a quiet host, warmup-dominated) — generous
        # headroom for CI contention
        assert elapsed < 300, f"notice-shrink smoke took {elapsed:.0f}s"


@pytest.mark.slow
class TestBenchProgressiveAB:
    """ISSUE 15's bench contract: `PROGRESSIVE=1 python bench.py` prints
    the progressive A/B row (fixed-res arm vs per-phase ms_per_step +
    switch_ms, driven through the shipped PhaseRuntime) and a standalone
    256px single-phase row, both BEFORE the headline row. Slow tier:
    a 256px compile in a subprocess."""

    def test_progressive_rows_before_headline(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_PLATFORM="cpu",
                   BENCH_BATCH="4", BENCH_STEPS="2", BENCH_WINDOWS="1",
                   BENCH_DEVSTEP="0", BENCH_SIZE="16", PROGRESSIVE="1",
                   BENCH_PROGRESSIVE_STEPS="2", BENCH_256_BATCH="2",
                   BENCH_256_STEPS="1")
        res = subprocess.run([sys.executable, "bench.py"], cwd=REPO,
                             env=env, capture_output=True, text=True,
                             timeout=900)
        assert res.returncode == 0, (res.stdout[-800:], res.stderr[-800:])
        rows = [json.loads(l) for l in res.stdout.splitlines()
                if l.startswith("{")]
        # both extra rows precede the headline row (last-line parse)
        ab = next(r for r in rows if "progressive" in r["metric"])
        r256 = next(r for r in rows if r["metric"].startswith("DCGAN-256"))
        assert rows.index(ab) < len(rows) - 1
        assert rows.index(r256) < len(rows) - 1
        assert ab["switch_ms"] > 0 and ab["carried_leaves"] > 0
        assert ab["fixed16"]["ms_per_step"] > 0
        assert ab["phase_r16"]["ms_per_step"] > 0
        assert ab["phase_r32"]["ms_per_step"] > 0
        assert r256["ms_per_step"] > 0 and r256["peak_state_mib"] > 0


@pytest.mark.slow
class TestBenchZeroAB:
    """ISSUE 13's bench contract: `ZERO_STAGE=3 python bench.py` prints
    the state-sharding A/B row (before the headline row) with
    peak_state_mib per arm STRICTLY DECREASING from stage 1 -> 3 —
    the ZeRO win as a number, not a claim. Slow tier: six multi-device
    step compiles in a subprocess."""

    def test_zero_ab_row_state_strictly_decreasing(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_PLATFORM="cpu",
                   BENCH_BATCH="8", BENCH_STEPS="4", BENCH_WINDOWS="1",
                   BENCH_ZERO_STEPS="3", BENCH_DEVSTEP="0",
                   BENCH_SIZE="16", ZERO_STAGE="3",
                   XLA_FLAGS="--xla_force_host_platform_device_count=2")
        res = subprocess.run([sys.executable, "bench.py"], cwd=REPO,
                             env=env, capture_output=True, text=True,
                             timeout=600)
        assert res.returncode == 0, (res.stdout[-800:], res.stderr[-800:])
        rows = [json.loads(l) for l in res.stdout.splitlines()
                if l.startswith("{")]
        # the A/B row precedes the headline row (last-line parse contract)
        ab = next(r for r in rows if "ZeRO" in r["metric"])
        assert rows[-1]["metric"].endswith("(batch 8/chip, bf16)")
        mibs = [ab[f"zero{s}"]["peak_state_mib"] for s in (1, 2, 3)]
        assert mibs[0] > mibs[1] > mibs[2], mibs
        # headline row carries the per-chip resident state too
        assert rows[-1]["peak_state_mib"] == pytest.approx(mibs[0])


@pytest.mark.chaos
class TestBenchStartupSmoke:
    """tools/bench_startup.py --smoke pinned into tier-1 (ISSUE 5,
    mirroring the chaos_drill pattern): the cold-vs-warm trainer A/B must
    keep proving the warm-start invariants end to end through real trainer
    subprocesses — warm compile strictly lower with a primed cache, zero
    warm cache misses, and the fused verified restore reading each
    manifest byte exactly once — inside an explicit runtime budget so the
    pin can never quietly eat the tier. The full-size run is standalone:
    `JAX_PLATFORMS=cpu python tools/bench_startup.py`."""

    def test_cold_warm_ab_passes_within_budget(self):
        import time

        t0 = time.monotonic()
        res = subprocess.run(
            [sys.executable, "tools/bench_startup.py", "--smoke"], cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=420)
        elapsed = time.monotonic() - t0
        assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-500:])
        row = json.loads(res.stdout.strip().splitlines()[-1])
        assert row["label"] == "bench-startup" and row["ok"] is True
        assert row["checks"]["warm_compile_strictly_lower"]
        assert row["checks"]["warm_zero_misses"]
        assert row["checks"]["restore_bytes_read_once"]
        assert row["warm"]["cache"]["hits"] > 0
        # the cross-topology arm (ISSUE 12): save@2-dev -> restore@1-dev
        # must take the sidecar reshard path, and the same-topology warm
        # arm must NOT
        assert row["checks"]["cross_resharded"]
        assert row["checks"]["warm_no_reshard"]
        assert row["cross"]["reshard_ms"] > 0
        # three tiny trainer subprocesses; ~4x measured cost (quiet host)
        assert elapsed < 240, f"bench_startup smoke took {elapsed:.0f}s"


@pytest.mark.chaos
class TestBenchServeSmoke:
    """tools/bench_serve.py --smoke pinned into tier-1 (ISSUE 9, the
    chaos-marker pattern): the cold-vs-warm serving A/B over a bursty
    Poisson trace must keep proving the serving-plane invariants end to
    end through real subprocesses — zero sampler recompiles after the
    AOT bucket warmup on BOTH arms (every served batch hits a
    precompiled bucket), warm cache hits with zero misses, and the
    finite-trace drain losing nothing — inside an explicit runtime
    budget so the pin can never quietly eat the tier. The full-size run
    is standalone: `JAX_PLATFORMS=cpu python tools/bench_serve.py`."""

    def test_cold_warm_serve_ab_passes_within_budget(self):
        import time

        t0 = time.monotonic()
        res = subprocess.run(
            [sys.executable, "tools/bench_serve.py", "--smoke"], cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=420)
        elapsed = time.monotonic() - t0
        assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-500:])
        row = json.loads(res.stdout.strip().splitlines()[-1])
        assert row["label"] == "bench-serve" and row["ok"] is True
        assert row["checks"]["cold_zero_recompiles_after_warmup"]
        assert row["checks"]["warm_zero_recompiles_after_warmup"]
        assert row["checks"]["warm_has_hits"]
        assert row["checks"]["warm_zero_misses"]
        assert row["cold"]["p99_ms"] >= row["cold"]["p50_ms"] > 0
        assert row["warm"]["completed"] == row["trace"]["requests"]
        # three tiny subprocesses (1 trainer + 2 serve arms, ~40 s on a
        # quiet host, compile-dominated); ~4x headroom for CI contention
        assert elapsed < 240, f"bench_serve smoke took {elapsed:.0f}s"

    def test_serve_drain_scenario_within_budget(self):
        """chaos_drill serve-drain pinned alongside: SIGTERM mid-load ->
        in-flight requests complete, queue drains, clean exit (the
        serving plane's first chaos consumer)."""
        import time

        t0 = time.monotonic()
        res = subprocess.run(
            [sys.executable, "tools/chaos_drill.py", "--only",
             "serve-drain"], cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=420)
        elapsed = time.monotonic() - t0
        lines = [json.loads(l) for l in res.stdout.splitlines()
                 if l.startswith("{")]
        summary = lines[-1]
        assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-500:])
        assert summary["scenarios"] == 1 and summary["failed"] == 0
        scenario = next(p for p in lines if p.get("scenario") == "serve-drain")
        assert scenario["clean_exit"] is True
        assert scenario["completed"] == scenario["submitted"] > 0
        # two tiny subprocesses (1 trainer + 1 serve under SIGTERM);
        # ~4x headroom for CI contention
        assert elapsed < 240, f"serve-drain smoke took {elapsed:.0f}s"


@pytest.mark.chaos
class TestFleetReplicaKillSmoke:
    """ISSUE 19's tier-1 pin (chaos-marker pattern): the serving fleet
    under live fire through a real `python -m dcgan_tpu.serve --fleet 3`
    subprocess — a chaos kill of replica 1 mid-trace must become a
    failover (ZERO failed client requests, completed == submitted), the
    dead replica must be drained from rotation and logged, and the
    mid-trace checkpoint injection must be hot-swapped onto EXACTLY the
    survivors with compile_requests_delta == 0 per replica (the
    zero-recompile promotion literal, proven by the compile-cache
    monitor, not assumed). Inside an explicit runtime budget so the pin
    can never quietly eat the tier."""

    def test_fleet_replica_kill_within_budget(self):
        import time

        t0 = time.monotonic()
        res = subprocess.run(
            [sys.executable, "tools/chaos_drill.py", "--only",
             "fleet-replica-kill"], cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True, timeout=420)
        elapsed = time.monotonic() - t0
        lines = [json.loads(l) for l in res.stdout.splitlines()
                 if l.startswith("{")]
        summary = lines[-1]
        assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-500:])
        assert summary["scenarios"] == 1 and summary["failed"] == 0
        row = next(p for p in lines
                   if p.get("scenario") == "fleet-replica-kill")
        assert row["failed"] == 0
        assert row["completed"] == row["submitted"] > 0
        assert row["unhealthy"] == [1]
        assert row["promoted_replicas"] == [0, 2]
        assert row["promoted_step"] == 2
        assert row["compile_requests_delta"] == 0
        # three tiny subprocesses (2 trainer runs for the checkpoint
        # lineage + 1 fleet serve; ~21 s measured total on a quiet
        # 1-core host); ~4x headroom for CI contention
        assert elapsed < 240, f"fleet-replica-kill took {elapsed:.0f}s"


@pytest.mark.slow
class TestToolsRunOnCpu:
    def test_loader_scale_two_processes(self):
        """The multi-process loader-scaling tool end to end on tiny shards:
        two workers own disjoint `shard_for_process` slices, measure over
        one shared wall window, and the parent emits well-formed aggregate
        rows (the numbers only mean anything on a quiet multi-core host —
        the contract here is protocol + JSON shape)."""
        res = subprocess.run(
            [sys.executable, "tools/bench_loader_scale.py",
             "--processes", "1", "2", "--seconds", "1.5", "--warmup_s", "5",
             "--num_examples", "512", "--num_shards", "8", "--threads",
             "4"],
            cwd=REPO, env=dict(os.environ), capture_output=True, text=True,
            timeout=300)
        assert res.returncode == 0, res.stderr[-800:]
        lines = [json.loads(l) for l in res.stdout.splitlines()
                 if l.startswith("{")]
        assert [p["processes"] for p in lines] == [1, 2]
        for p in lines:
            assert p["label"] == "loader-scale"
            assert len(p["per_process_images_per_sec"]) == p["processes"]
            assert p["aggregate_images_per_sec"] == pytest.approx(
                sum(p["per_process_images_per_sec"]), abs=0.5)
            assert p["cores_visible"] >= 1

    def test_canonical_50k_tool_cpu(self):
        """tools/canonical_50k.py end to end at toy scale: random torch
        tower -> convert_torch_embedder .npz -> step-0 checkpoint ->
        `python -m dcgan_tpu.evals --feature_npz` — the exact pipeline the
        chip row in BASELINE.md certifies at 50k, pinned here so the tool
        cannot rot (the score is arbitrary; the contract is that the
        canonical path executes and reports the requested sample count)."""
        res = subprocess.run(
            [sys.executable, "tools/canonical_50k.py"], cwd=REPO,
            env=dict(os.environ, BENCH_PLATFORM="cpu", JAX_PLATFORMS="cpu",
                     CANON_SAMPLES="64"),
            capture_output=True, text=True, timeout=900)
        assert res.returncode == 0, (res.stderr[-800:], res.stdout[-300:])
        row = json.loads(res.stdout.strip().splitlines()[-1])
        assert row["label"] == "canonical-npz-50k"
        assert row["num_samples"] == 64
        assert row["fid"] > 0 and row["feature_dim"] == 512
        assert "torch" in row["embedder"]

    def test_matmul_rate_cpu(self):
        env = dict(os.environ, BENCH_PLATFORM="cpu", JAX_PLATFORMS="cpu",
                   MATMUL_SHAPES="64x64,64x128", MATMUL_ITERS="2",
                   MATMUL_WINDOWS="1")
        res = subprocess.run(
            [sys.executable, "tools/matmul_rate.py"], cwd=REPO, env=env,
            capture_output=True, text=True, timeout=300)
        assert res.returncode == 0, res.stderr[-500:]
        lines = [json.loads(l) for l in res.stdout.splitlines()
                 if l.startswith("{")]
        shapes = [(p["m"], p["n"]) for p in lines if p.get("form")]
        assert shapes == [(64, 64), (64, 128)]
        summ = lines[-1]
        assert summ["label"] == "matmul-rate" and summ["peak_tflops"] > 0

    def test_attention_memory_cpu(self):
        """attention_memory compiles both forms and prints well-formed
        rows; where the backend reports temp sizes, dense must grow with
        S while flash stays bounded (the O(S^2)-vs-O(S) claim's shape)."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        res = subprocess.run(
            [sys.executable, "tools/attention_memory.py",
             "--platform", "cpu", "--seq", "256", "512"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
        assert res.returncode == 0, res.stderr[-500:]
        lines = [json.loads(l) for l in res.stdout.splitlines()
                 if l.startswith("{")]
        rows = {(p["form"], p["seq"]): p for p in lines if "form" in p}
        assert set(rows) == {("dense", 256), ("dense", 512),
                             ("flash", 256), ("flash", 512)}
        # both forms must actually compile on CPU — an error row also
        # carries form/seq, so key equality alone would mask a regression
        for key, p in rows.items():
            assert "error" not in p, (key, p)
        d256 = rows[("dense", 256)].get("temp_mib")
        d512 = rows[("dense", 512)].get("temp_mib")
        if d256 is not None and d512 is not None and d512 > 0:
            assert d512 >= d256

    def test_step_profile_cpu(self):
        env = dict(os.environ, BENCH_PLATFORM="cpu", JAX_PLATFORMS="cpu",
                   BENCH_BATCH="8", BENCH_SCAN="2", BENCH_WINDOWS="1",
                   # keep CALLS (= BENCH_STEPS//BENCH_SCAN) at 2 — the
                   # sync-amortization default of 400 steps/window is a
                   # chip policy, ~200x the acceptable CPU smoke work
                   BENCH_STEPS="4")
        res = subprocess.run(
            [sys.executable, "tools/step_profile.py"], cwd=REPO, env=env,
            capture_output=True, text=True, timeout=600)
        assert res.returncode == 0, res.stderr[-500:]
        lines = [json.loads(l) for l in res.stdout.splitlines()
                 if l.startswith("{")]
        comps = {p["component"] for p in lines if "component" in p}
        assert comps == {"train_step", "fwd_losses", "g_forward",
                         "adam_applies"}
        summ = lines[-1]
        assert summ["label"] == "step-profile"
        assert summ["step_ms"] > 0 and summ["fwd_ms"] > 0


class TestBenchEnvLabels:
    """bench_model_config's label is the join key between capture rows and
    step_profile rooflines; it must reflect the attention that actually
    runs AFTER the BENCH_ATTN_RES override (ADVICE r5 #2)."""

    def _label(self, **env):
        from dcgan_tpu.utils.bench_env import bench_model_config
        return bench_model_config(env)[1]

    def test_base_labels_unchanged(self):
        assert self._label() == "headline"
        assert self._label(BENCH_SIZE="128") == "dcgan128"
        assert self._label(BENCH_ATTN="1") == "sagan64-attn"
        assert self._label(BENCH_ATTN="1", BENCH_PALLAS="1",
                           BENCH_BN_PALLAS="0") == "sagan64-attn-flash"
        assert self._label(BENCH_PALLAS="1") == "headline-pallas"
        assert self._label(BENCH_PALLAS="1", BENCH_BN_PALLAS="0") \
            == "headline-pallas-xlabn"
        assert self._label(BENCH_ATTN="1", BENCH_SN="1") \
            == "sagan64-attn-sn"

    def test_attn_res_override_labels_match_bench_matrix(self):
        """The ADVICE r5 #2 scenario: a BENCH_ATTN_RES config running flash
        attention must not be labeled '-pallas-xlabn' (declared
        no-Pallas-kernel-runs); long-context labels match capture_all's
        '<family>-attn<R>-{flash,dense}' naming."""
        assert self._label(BENCH_SIZE="256", BENCH_ATTN_RES="128",
                           BENCH_PALLAS="1", BENCH_BN_PALLAS="0") \
            == "dcgan256-attn128-flash"
        assert self._label(BENCH_SIZE="256", BENCH_ATTN_RES="128") \
            == "dcgan256-attn128-dense"
