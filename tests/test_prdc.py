"""Precision/recall/density/coverage (evals/prdc.py): k-NN manifold
estimators separating fidelity from diversity — properties FID/KID
compress into one number."""

import numpy as np
import pytest

from dcgan_tpu.evals.prdc import _knn_radii_sq, _pairwise_sq_dists, prdc


def _blob(rng, n, d=8, loc=0.0, scale=1.0):
    return rng.normal(loc=loc, scale=scale, size=(n, d)).astype(np.float32)


class TestHelpers:
    def test_pairwise_matches_naive(self):
        rng = np.random.default_rng(0)
        a, b = _blob(rng, 37, 5), _blob(rng, 23, 5)
        d = _pairwise_sq_dists(a, b, block=16)  # force multiple blocks
        naive = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        np.testing.assert_allclose(d, naive, rtol=1e-4, atol=1e-4)

    def test_knn_radii_exclude_self(self):
        # 3 points on a line at 0, 1, 10: k=1 radii are the nearest OTHER
        x = np.asarray([[0.0], [1.0], [10.0]], np.float32)
        r = _knn_radii_sq(x, k=1)
        np.testing.assert_allclose(r, [1.0, 1.0, 81.0])

    def test_k_validated(self):
        x = np.zeros((4, 2), np.float32)
        with pytest.raises(ValueError, match="k must be"):
            _knn_radii_sq(x, k=4)
        with pytest.raises(ValueError, match="k must be"):
            _knn_radii_sq(x, k=0)


class TestPRDC:
    def test_identical_sets_perfect_scores(self):
        rng = np.random.default_rng(1)
        x = _blob(rng, 200)
        out = prdc(x, x, k=5)
        assert out["precision"] == 1.0
        assert out["recall"] == 1.0
        assert out["coverage"] == 1.0
        assert out["density"] >= 1.0  # each point sits in >= k balls of x

    def test_disjoint_sets_zero_scores(self):
        rng = np.random.default_rng(2)
        real = _blob(rng, 200, loc=0.0, scale=0.5)
        fake = _blob(rng, 200, loc=50.0, scale=0.5)
        out = prdc(real, fake, k=5)
        assert out["precision"] == 0.0
        assert out["recall"] == 0.0
        assert out["density"] == 0.0
        assert out["coverage"] == 0.0

    def test_mode_collapse_high_precision_low_recall(self):
        """The separation FID cannot make: a collapsed generator emitting
        one realistic mode scores high precision (samples are realistic)
        but low recall/coverage (the distribution is not covered)."""
        rng = np.random.default_rng(3)
        real = _blob(rng, 400, scale=2.0)
        # fakes = tiny jitter around ONE real point
        center = real[7]
        fake = (center[None, :]
                + 0.01 * rng.normal(size=(400, 8))).astype(np.float32)
        out = prdc(real, fake, k=5)
        assert out["precision"] > 0.9
        assert out["recall"] < 0.2
        assert out["coverage"] < 0.2

    def test_shape_validation(self):
        with pytest.raises(ValueError, match="feature sets"):
            prdc(np.zeros((10, 4), np.float32),
                 np.zeros((10, 5), np.float32))

    def test_compute_fid_integration(self):
        """prdc=True rides the same reservoirs as KID inside compute_fid."""
        import jax.numpy as jnp

        from dcgan_tpu.evals.job import compute_fid

        def sample_fn(z):
            # generator emitting uniform noise images like the data stream
            import jax

            return jax.random.uniform(jax.random.key(int(z.sum()) % 997),
                                      (z.shape[0], 8, 8, 3),
                                      minval=-1.0, maxval=1.0)

        def data():
            rng = np.random.default_rng(0)
            while True:
                yield jnp.asarray(rng.uniform(-1, 1, (32, 8, 8, 3)),
                                  jnp.float32)

        out = compute_fid(sample_fn, data(), image_size=8, num_samples=128,
                          batch_size=32, prdc=True, prdc_k=3,
                          kid_pool_size=128)
        for key in ("precision", "recall", "density", "coverage"):
            assert key in out and 0.0 <= out[key]
        assert out["precision"] > 0.0  # same distribution: manifolds overlap
