"""ZeRO-2/3 state-sharded training (ISSUE 13, arXiv:2004.13336).

The `--zero_stage {1,2,3}` knob extends ZeRO-1 (`shard_opt`) to gradient
and parameter sharding over the data axis on BOTH backends: stage 2
reduce-scatters gradients into rule-engine shards, runs Adam shard-local
against the already-sharded moments, and rebuilds replicated params with
one fused all-gather per update; stage 3 additionally keeps params and
the EMA mirror resident sharded between steps with a just-in-time
all-gather inside each forward.

Stage-1 parity (the `--zero_stage 1` default must be byte-identical to
pre-PR behavior) is pinned MECHANICALLY, not by an A/B of the binary
against itself: every stage-1 program's jaxpr fingerprint in the
committed `analysis/programs.lock.jsonl` is unchanged from the pre-ZeRO
manifest (the semantic smoke pin in tests/test_tools.py recomputes and
byte-compares it), and the rule engine's stage-1 resolution still matches
the retired hand-built oracle spec-object-for-spec-object
(tests/test_elastic.py). What THIS file pins:

- stage 1/2/3 loss parity on the canonical 2-device CPU mesh for all
  three model families, both backends, with per-chip resident state
  strictly decreasing 1 -> 2 -> 3;
- the donation-aliasing contract for every sharded-grad program (both
  backends, both LR-backoff variants) via the committed manifest;
- warmup-plan completeness for every stage variant;
- the zero_stage config validation (stage >= 2 needs a data axis of
  size > 1; an unshardable targeted leaf fails loudly, named);
- device-resident rollback snapshots of ZeRO-sharded state.

The end-to-end NaN-rollback drill (zero_stage=3 vs a stage-1 control,
bit-exact loss replay) lives in tools/chaos_drill.py::zero-rollback,
pinned by tests/test_tools.py; the cross-stage cross-mesh checkpoint
restore lives in tests/test_elastic.py.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
from dcgan_tpu.elastic import rules
from dcgan_tpu.parallel import make_parallel_train
from dcgan_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

TINY = dict(output_size=16, gf_dim=8, df_dim=8, compute_dtype="float32")

#: the three trainable families at the tiny preset; resnet/stylegan pair
#: with the hinge loss (their BN-free critic recipe)
FAMILIES = {
    "dcgan": dict(model=ModelConfig(**TINY), loss="gan"),
    "resnet": dict(model=ModelConfig(arch="resnet", **TINY), loss="hinge"),
    "stylegan": dict(model=ModelConfig(arch="stylegan", spectral_norm="d",
                                       **TINY), loss="hinge"),
}


def _mesh2():
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:2]).reshape(2, 1),
                (DATA_AXIS, MODEL_AXIS))


def _batch():
    rng = np.random.default_rng(0)
    return jnp.asarray(np.tanh(rng.normal(size=(8, 16, 16, 3)))
                       .astype(np.float32))


def _state_mib_per_chip(state) -> float:
    """THE derivation bench.py's peak_state_mib ships (one shared
    definition — the test pins the real metric, not a copy)."""
    from dcgan_tpu.parallel.sharding import state_bytes_per_chip

    return state_bytes_per_chip(state) / 2**20


def _run(backend: str, family: str, stage: int, steps: int = 3):
    cfg = TrainConfig(batch_size=8, backend=backend,
                      mesh=MeshConfig(data=2, zero_stage=stage),
                      **FAMILIES[family])
    pt = make_parallel_train(cfg, _mesh2())
    state = pt.init(jax.random.key(0))
    mib = _state_mib_per_chip(state)
    xs = _batch()
    rows = []
    for i in range(steps):
        state, m = pt.step(state, xs,
                           jax.random.fold_in(jax.random.key(1), i))
        rows.append([float(v) for _, v in sorted(m.items())])
    return np.asarray(rows), mib, state


class TestLossParity:
    """Stages 2/3 must train the stage-1 trajectory: the sharding is a
    LAYOUT of the same computation (reduce-scatter + shard-local Adam +
    all-gather == all-reduce + replicated Adam), so losses track stage 1
    to f32 reduction-order noise — and the per-chip resident state
    strictly decreases 1 -> 2 -> 3, which is the point of the ladder."""

    # one smoke cell per backend; the full family matrix is slow-tier
    # (every cell is two fresh multi-device compiles)
    @pytest.mark.parametrize("backend,family", [
        pytest.param("gspmd", "dcgan", id="gspmd-dcgan"),
        pytest.param("shard_map", "dcgan", id="shard_map-dcgan"),
        pytest.param("gspmd", "resnet", id="gspmd-resnet",
                     marks=pytest.mark.slow),
        pytest.param("shard_map", "resnet", id="shard_map-resnet",
                     marks=pytest.mark.slow),
        pytest.param("gspmd", "stylegan", id="gspmd-stylegan",
                     marks=pytest.mark.slow),
        pytest.param("shard_map", "stylegan", id="shard_map-stylegan",
                     marks=pytest.mark.slow),
    ])
    def test_stage_ladder_loss_parity_and_memory(self, backend, family):
        rows1, mib1, _ = _run(backend, family, 1)
        rows2, mib2, _ = _run(backend, family, 2)
        rows3, mib3, _ = _run(backend, family, 3)
        np.testing.assert_allclose(rows2, rows1, rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(rows3, rows1, rtol=1e-3, atol=1e-3)
        assert mib1 > mib2 > mib3, (mib1, mib2, mib3)

    def test_stage3_residency(self):
        """Stage 3's memory model, asserted on the physical shards: Adam
        moments AND params AND the EMA mirror each hold 1/2 per device on
        the 2-way data axis; stage 2 shards only the moments."""
        _, _, s2 = _run("gspmd", "dcgan", 2, steps=1)
        _, _, s3 = _run("gspmd", "dcgan", 3, steps=1)
        for state, param_sharded in ((s2, False), (s3, True)):
            mu = state["opt"]["disc"][1][0].mu["conv1"]["w"]
            assert {int(np.prod(sh.data.shape))
                    for sh in mu.addressable_shards} \
                == {mu.size // 2}
            for leaf in (state["params"]["disc"]["conv1"]["w"],
                         state["ema_gen"]["deconv1"]["w"]):
                frac = {int(np.prod(sh.data.shape))
                        for sh in leaf.addressable_shards}
                assert frac == {leaf.size // (2 if param_sharded else 1)}


class TestDonationAudit:
    """DCG007's answer for the sharded-grad programs, read from the
    committed manifest (the semantic smoke pin recomputes it live): every
    donated data-SHARDED state leaf is realized as an input_output_alias
    pair — in BOTH backends, at BOTH stages, including the LR-backoff
    rebuild variants. A donated-but-unaliased sharded leaf would be a
    silent full-shard copy per step, exactly the overhead ZeRO exists to
    remove."""

    def _zero_rows(self):
        from dcgan_tpu.analysis import manifest as mlib

        recs = mlib.load_path(mlib.default_manifest_path())
        return [r for r in recs if "@zero" in r.name]

    def test_every_stage_variant_is_in_the_manifest(self):
        names = {r.name for r in self._zero_rows()}
        for backend in ("gspmd", "shard_map"):
            for stage in (2, 3):
                for prog in ("train_step", "multi_step@k2", "d_update",
                             "g_update", "gen_fakes"):
                    assert f"{backend}::{prog}@zero{stage}" in names
                for prog in ("train_step", "multi_step@k2", "d_update",
                             "g_update"):
                    assert (f"{backend}::{prog}@lr_backoff@zero{stage}"
                            in names)

    def test_donated_sharded_leaves_all_alias(self):
        donating = [r for r in self._zero_rows() if r.donation is not None]
        # 4 programs x 2 backoffs x 2 stages x 2 backends = 32, plus the
        # collective-overlap variants (ISSUE 20): 4 donated programs x
        # 2 backoffs x 3 arms (zero2@overlap, zero3@overlap,
        # zero3@prefetch) = 24
        assert len(donating) == 56
        for r in donating:
            assert r.donation["unaliased"] == [], r.name
            assert r.donation["aliased"] == r.donation["donated"] > 0, \
                r.name

    def test_shard_map_census_shows_the_zero_collectives(self):
        """The explicit-collective backend's rows carry the ZeRO wire
        pattern: reduce-scatter gradients at both stages, strictly MORE
        all-gathers at stage 3 (the just-in-time param gathers)."""
        rows = {r.name: r for r in self._zero_rows()}
        for stage in (2, 3):
            c = rows[f"shard_map::train_step@zero{stage}"].collectives
            assert c.get("reduce_scatter", 0) > 0
            assert c.get("all_gather", 0) > 0
        assert (rows["shard_map::train_step@zero3"].collectives[
                    "all_gather"]
                > rows["shard_map::train_step@zero2"].collectives[
                    "all_gather"])
        # stage 3's fill program gathers the sharded G params; stage 2's
        # reads them replicated
        assert rows["shard_map::gen_fakes@zero3"].collectives.get(
            "all_gather", 0) > 0
        assert rows["shard_map::gen_fakes@zero2"].collectives.get(
            "all_gather", 0) == 0


class TestWarmupPlanCompleteness:
    """Every stage variant's warmup plan must enumerate what its loop
    dispatches (DESIGN §6d: the first live dispatch of an unplanned
    program would compile under an armed watchdog deadline)."""

    def _cfg(self, backend, stage, pipeline=False):
        return TrainConfig(
            model=ModelConfig(**TINY), batch_size=8, backend=backend,
            mesh=MeshConfig(data=2, zero_stage=stage),
            steps_per_call=1 if pipeline else 2, pipeline_gd=pipeline,
            sample_every_steps=100, activation_summary_steps=100,
            nan_check_steps=100, nan_policy="rollback",
            rollback_snapshot_steps=100, rollback_lr_backoff=0.5,
            tensorboard=False)

    @pytest.mark.parametrize("backend", ["gspmd", "shard_map"])
    @pytest.mark.parametrize("stage", [2, 3])
    def test_plan_covers_the_stage_variants(self, backend, stage):
        from dcgan_tpu.train import warmup

        mesh = _mesh2()
        cfg = self._cfg(backend, stage)
        pt = make_parallel_train(cfg, mesh)
        state = warmup.state_example(pt)
        z = jax.ShapeDtypeStruct((8, cfg.model.z_dim), jnp.float32)
        plan, pt_backoff = warmup.build_warmup_plan(
            cfg, pt, state, sample_z=z, eval_z=z,
            make_backoff_pt=lambda c: make_parallel_train(c, mesh))
        names = [n for n, _, _ in plan]
        for want in ("train_step", "multi_step@k2", "sampler",
                     "eval_losses", "summarize", "state_copy",
                     "train_step@lr_backoff", "multi_step@k2@lr_backoff"):
            assert want in names, (backend, stage, names)
        assert pt_backoff is not None

        cfg_p = self._cfg(backend, stage, pipeline=True)
        pt_p = make_parallel_train(cfg_p, mesh)
        plan_p, _ = warmup.build_warmup_plan(
            cfg_p, pt_p, warmup.state_example(pt_p),
            make_backoff_pt=lambda c: make_parallel_train(c, mesh))
        names_p = [n for n, _, _ in plan_p]
        for want in ("gen_fakes", "d_update", "g_update",
                     "d_update@lr_backoff", "g_update@lr_backoff"):
            assert want in names_p, (backend, stage, names_p)


class TestConfigValidation:
    def test_stage_out_of_range(self):
        with pytest.raises(ValueError, match="zero_stage"):
            MeshConfig(zero_stage=0)
        with pytest.raises(ValueError, match="zero_stage"):
            MeshConfig(zero_stage=4)

    def test_stage_rejects_spatial(self):
        with pytest.raises(ValueError, match="spatial"):
            MeshConfig(model=2, spatial=True, zero_stage=2)

    def test_shard_map_rejects_grad_clip_under_zero(self):
        with pytest.raises(ValueError, match="global norm"):
            TrainConfig(model=ModelConfig(**TINY), backend="shard_map",
                        grad_clip=1.0, mesh=MeshConfig(zero_stage=2))

    @pytest.mark.parametrize("backend", ["gspmd", "shard_map"])
    def test_stage2_requires_data_axis_gt_1(self, backend):
        from jax.sharding import Mesh

        mesh1 = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1),
                     (DATA_AXIS, MODEL_AXIS))
        cfg = TrainConfig(model=ModelConfig(**TINY), batch_size=8,
                          backend=backend,
                          mesh=MeshConfig(data=1, zero_stage=2))
        with pytest.raises(ValueError, match="data axis"):
            make_parallel_train(cfg, mesh1)

    def test_divisibility_error_names_the_offending_leaf(self):
        """A targeted leaf with >= 2x the data axis's elements but no dim
        the axis divides must fail loudly, NAMING the leaf — not silently
        degrade the stage's memory model."""
        shapes = {"opt": {"g": {"proj": {
            "w": jax.ShapeDtypeStruct((5, 5), jnp.float32)}}}}
        with pytest.raises(ValueError, match=r"opt/g/proj/w"):
            rules.validate_zero_state(shapes, {"data": 2, "model": 1},
                                      zero_stage=2)
        # the same leaf is fine at stage 1 (nothing targets it) and when
        # a dim divides
        rules.validate_zero_state(shapes, {"data": 2, "model": 1},
                                  zero_stage=1)
        ok = {"opt": {"g": {"proj": {
            "w": jax.ShapeDtypeStruct((5, 6), jnp.float32)}}}}
        rules.validate_zero_state(ok, {"data": 2, "model": 1},
                                  zero_stage=2)

    def test_shard_map_now_accepts_zero_stages(self):
        # the pre-ISSUE-13 blanket rejection narrowed to shard_opt only
        cfg = TrainConfig(model=ModelConfig(**TINY), backend="shard_map",
                          mesh=MeshConfig(zero_stage=3))
        assert cfg.mesh.zero_stage == 3


class TestGradSpecDerivation:
    """`rules.grad_shardings` / `zero_scatter_dims`: the gradient specs
    derive from the SAME rule table as mu/nu (the ISSUE's contract — the
    reduce-scattered gradient is the shard-local update's input with zero
    re-layout)."""

    def _param_shapes(self):
        from dcgan_tpu.train.steps import init_train_state

        cfg = TrainConfig(model=ModelConfig(**TINY), batch_size=8)
        return jax.eval_shape(lambda k: init_train_state(k, cfg),
                              jax.random.key(0))

    def test_grad_specs_match_moment_specs(self):
        shapes = self._param_shapes()
        mesh_shape = {"data": 2, "model": 1}
        sharded = 0
        for net in ("gen", "disc"):
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                    shapes["params"][net])[0]:
                tail = rules.path_str(path)
                shape = tuple(leaf.shape)
                gspec = rules.resolve_spec(
                    rules.logical_spec(tail, len(shape)), shape,
                    mesh_shape, zero=True)
                mspec = rules.resolve_spec(
                    rules.logical_spec(f"opt/{net}/1/0/mu/{tail}",
                                       len(shape)),
                    shape, mesh_shape, zero=True)
                assert gspec == mspec, (net, tail)
                if any(a == DATA_AXIS
                       or (isinstance(a, tuple) and DATA_AXIS in a)
                       for a in gspec):
                    sharded += 1
        assert sharded >= 10  # the policy really shards the heavy leaves

    def test_scatter_dims_match_shardings(self):
        """The shard_map backend's explicit collective dims agree with
        the NamedSharding derivation: the dim carrying the data axis in
        the resolved spec IS the psum_scatter/all_gather dim, and the
        dims tree maps one-to-one onto the param tree (what the backend's
        tree_map against gradient trees rides on)."""
        shapes = self._param_shapes()
        mesh_shape = {"data": 2, "model": 1}
        for net in ("gen", "disc"):
            dims = rules.zero_scatter_dims(shapes["params"][net],
                                           mesh_shape)
            assert jax.tree_util.tree_structure(dims) == \
                jax.tree_util.tree_structure(
                    jax.tree_util.tree_map(lambda _: 0,
                                           shapes["params"][net]))
            for (path, leaf), d in zip(
                    jax.tree_util.tree_flatten_with_path(
                        shapes["params"][net])[0],
                    jax.tree_util.tree_leaves(dims)):
                tail = rules.path_str(path)
                shape = tuple(leaf.shape)
                spec = rules.resolve_spec(
                    rules.logical_spec(tail, len(shape)), shape,
                    mesh_shape, zero=True)
                data_dims = [i for i, a in enumerate(spec)
                             if a == DATA_AXIS
                             or (isinstance(a, tuple) and DATA_AXIS in a)]
                assert data_dims == ([] if d < 0 else [d]), (net, tail)


class TestPipelineZeroCompose:
    """--pipeline_gd x --zero_stage: the stage programs carry the same
    hooks (manifest rows d_update@zeroN / g_update@zeroN), so the
    pipelined dispatch loop trains the same trajectory sharded as
    replicated — bit-exact on the shard_map backend, whose explicit
    collectives reproduce the pmean arithmetic."""

    @pytest.mark.slow
    def test_pipelined_stage3_matches_pipelined_stage1(self):
        from dcgan_tpu.train.gd_pipeline import GDPipeline

        rows = {}
        for stage in (1, 3):
            cfg = TrainConfig(model=ModelConfig(**TINY), batch_size=8,
                              backend="shard_map", pipeline_gd=True,
                              mesh=MeshConfig(data=2, zero_stage=stage))
            pt = make_parallel_train(cfg, _mesh2())
            state = pt.init(jax.random.key(0))
            pipe = GDPipeline()
            xs = _batch()
            out = []
            for i in range(3):
                state, m = pipe.step(
                    pt, state, xs,
                    jax.random.fold_in(jax.random.key(1), i))
                out.append(sorted((k, float(v)) for k, v in m.items()))
            pipe.drain("test-end")
            rows[stage] = out
        assert rows[1] == rows[3]


class TestRollbackWithShardedState:
    """train/rollback.py under ZeRO-3 residency: both snapshot modes
    round-trip the data-sharded state with shardings AND values intact
    (the device-resident mode is what multi-host rollback dispatches; the
    host mode is the single-process drill's path)."""

    @pytest.mark.parametrize("device_resident", [True, False],
                             ids=["device-resident", "host"])
    def test_snapshot_restore_roundtrip(self, device_resident):
        from dcgan_tpu.train.rollback import RollbackManager

        cfg = TrainConfig(model=ModelConfig(**TINY), batch_size=8,
                          mesh=MeshConfig(data=2, zero_stage=3))
        pt = make_parallel_train(cfg, _mesh2())
        state = pt.init(jax.random.key(0))
        mgr = RollbackManager(every=1, max_rollbacks=1,
                              device_resident=device_resident)
        mgr.snapshot(0, state)
        restored, step = mgr.restore(FloatingPointError("test"))
        assert step == 0
        for (path, a), b in zip(
                jax.tree_util.tree_leaves_with_path(state),
                jax.tree_util.tree_leaves(restored)):
            # placement equivalence, not spec-object equality: the jit
            # identity copy canonicalizes away size-1 mesh axes
            # (P(..., 'data', 'model') -> P(..., 'data') on a model=1
            # mesh) without moving a byte
            assert a.sharding.is_equivalent_to(b.sharding, a.ndim), \
                jax.tree_util.keystr(path)
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
