"""Invariant analyzer (ISSUE 8): per-checker fixture suites, the
suppression/baseline machinery, the runtime tripwire, and the
full-package clean pin.

Each checker gets synthetic bad-code snippets that must produce exactly
their seeded finding, plus clean twins that must produce none — the
fixtures are the spec for what the AST heuristics resolve. Paths are
chosen to land inside (or outside) each checker's scope."""

import json
import threading

import numpy as np
import pytest

from dcgan_tpu.analysis import core, tripwire
from dcgan_tpu.analysis.parity import key_in_inventory


def run(snippets, checks=None, inventory=None, **cfg_kw):
    """snippets: {relpath: source} -> findings (suppressions applied)."""
    sources = [core.SourceFile.from_source(src, path)
               for path, src in snippets.items()]
    cfg = core.Config(inventory=inventory if inventory is not None else {},
                      **cfg_kw)
    return core.run_checks(sources, cfg, checks=checks)


# -- DCG001: collectives off the dispatch thread -----------------------------

class TestCollectiveThreads:
    BAD_THREAD = '''
import threading
from jax.experimental import multihost_utils

def worker():
    multihost_utils.process_allgather(1)

def start():
    threading.Thread(target=worker, daemon=True).start()
'''

    def test_thread_target_reaching_collective_flagged(self):
        fs = run({"dcgan_tpu/x.py": self.BAD_THREAD}, checks=["DCG001"])
        assert [f.check for f in fs] == ["DCG001"]
        assert fs[0].key == "worker->process_allgather"
        assert "dispatch thread" in fs[0].message

    def test_multi_hop_and_submit_root(self):
        src = '''
from jax import lax

def helper(x):
    return lax.psum(x, "data")

def task(x):
    return helper(x)

def main(svc, x):
    svc.submit(task)
'''
        fs = run({"dcgan_tpu/x.py": src}, checks=["DCG001"])
        assert [f.key for f in fs] == ["task->psum"]

    def test_cross_module_resolution(self):
        coord = '''
def anomaly_consensus(bad):
    return bad
'''
        user = '''
import threading
from dcgan_tpu.train.coordination import anomaly_consensus

def poller():
    anomaly_consensus(False)

def go():
    threading.Thread(target=poller).start()
'''
        fs = run({"dcgan_tpu/train/coordination.py": coord,
                  "dcgan_tpu/train/x.py": user}, checks=["DCG001"])
        assert [f.key for f in fs] == ["poller->anomaly_consensus"]

    def test_receiver_gating_save(self):
        # img.save is PIL, ckpt.save is a collective: only the checkpoint
        # receiver trips the generic method name
        src = '''
def grid_task(img, path):
    img.save(path)

def save_task(ckpt, step, state):
    ckpt.save(step, state)

def go(svc):
    svc.submit(grid_task)
    svc.submit(save_task)
'''
        fs = run({"dcgan_tpu/x.py": src}, checks=["DCG001"])
        assert [f.key for f in fs] == ["save_task->ckpt.save"]

    def test_positional_thread_target_slot(self):
        # Thread(group, target): the positional target is args[1]
        src = '''
import threading
from jax import lax

def worker():
    lax.psum(1, "data")

def go():
    threading.Thread(None, worker).start()
'''
        fs = run({"dcgan_tpu/x.py": src}, checks=["DCG001"])
        assert [f.key for f in fs] == ["worker->psum"]

    def test_pt_gating_is_whole_segment(self):
        # `opt.step` is an optimizer, `script.init` a helper — neither may
        # trip the pt-dispatch heuristic (substring matching once did)
        src = '''
def task(opt, script, grads):
    opt.step(grads)
    script.init()

def go(svc):
    svc.submit(task)
'''
        assert run({"dcgan_tpu/x.py": src}, checks=["DCG001"]) == []

    def test_clean_twin_host_local_tail(self):
        src = '''
import threading, json

def worker(rows, path):
    with open(path, "a") as f:
        for r in rows:
            f.write(json.dumps(r) + "\\n")

def start(rows, path):
    threading.Thread(target=worker, args=(rows, path)).start()
'''
        assert run({"dcgan_tpu/x.py": src}, checks=["DCG001"]) == []

    DISPATCH_OWNER = '''
import threading

class ServeWorker:
    def _run(self):
        self._ckpt.restore_latest(self._state)
        self._pt.sample(self._state, None)

    def start(self):
        threading.Thread(target=self._run).start()
'''

    def test_declared_dispatch_thread_target_exempt(self):
        """ISSUE 9: a thread target declared in
        Config.dispatch_thread_targets IS a dispatch thread by design
        (the serving plane's single worker owns every collective) — no
        finding; the same code undeclared still trips on the
        `restore_latest` terminal-name sink."""
        path = "dcgan_tpu/serve/w.py"
        flagged = run({path: self.DISPATCH_OWNER}, checks=["DCG001"])
        assert [f.key for f in flagged] == [
            "self._run->restore_latest"]
        clean = run({path: self.DISPATCH_OWNER}, checks=["DCG001"],
                    dispatch_thread_targets=(
                        f"{path}::ServeWorker._run",))
        assert clean == []

    def test_dispatch_owner_declaration_is_exact(self):
        """The allowlist matches path::QualName exactly — a different
        class or file with the same method name keeps tripping."""
        path = "dcgan_tpu/serve/w.py"
        fs = run({path: self.DISPATCH_OWNER}, checks=["DCG001"],
                 dispatch_thread_targets=(
                     "dcgan_tpu/serve/other.py::ServeWorker._run",
                     f"{path}::OtherWorker._run"))
        assert [f.check for f in fs] == ["DCG001"]

    def test_real_services_and_coordination_are_clean(self):
        sources = core.collect_sources(
            [core.default_root() + "/dcgan_tpu"], core.default_root())
        fs = core.run_checks(sources, core.Config(inventory={}),
                             checks=["DCG001"])
        assert fs == []


# -- DCG002: donation hazard -------------------------------------------------

class TestDonationHazard:
    def test_device_get_into_donating_jit_flagged(self):
        src = '''
import jax
step = jax.jit(lambda s: s, donate_argnums=(0,))

def resume(state):
    restored = jax.device_get(state)
    return step(restored)
'''
        fs = run({"dcgan_tpu/x.py": src}, checks=["DCG002"])
        assert [f.key for f in fs] == ["step(restored)"]

    def test_pt_dispatch_with_device_put_value_flagged(self):
        src = '''
import jax

def loop(pt, host_state, images, key):
    state = jax.device_put(host_state)
    state, metrics = pt.step(state, images, key)
    return state
'''
        fs = run({"dcgan_tpu/x.py": src}, checks=["DCG002"])
        assert [f.key for f in fs] == ["pt.step(state)"]

    def test_sanitized_twin_clean(self):
        src = '''
import jax
from dcgan_tpu.utils.checkpoint import owned_host_copy
step = jax.jit(lambda s: s, donate_argnums=(0,))

def resume(state):
    restored = owned_host_copy(state)
    return step(restored)

def rebased(mgr, abstract):
    from dcgan_tpu.utils.checkpoint import _rebase_onto_xla_buffers
    restored = _rebase_onto_xla_buffers(mgr.restore(abstract))
    return step(restored)
'''
        assert run({"dcgan_tpu/x.py": src}, checks=["DCG002"]) == []

    def test_non_donating_jit_clean(self):
        src = '''
import jax
probe = jax.jit(lambda s: s)

def peek(state):
    host = jax.device_get(state)
    return probe(host)
'''
        assert run({"dcgan_tpu/x.py": src}, checks=["DCG002"]) == []


# -- DCG003: raw shard_map ---------------------------------------------------

class TestRawShardMap:
    def test_import_and_attribute_flagged(self):
        src = '''
from jax.experimental.shard_map import shard_map
import jax

def use(f, mesh):
    return jax.shard_map(f, mesh=mesh)
'''
        fs = run({"dcgan_tpu/parallel/x.py": src}, checks=["DCG003"])
        assert {f.key for f in fs} == {"jax.experimental.shard_map",
                                       "jax.shard_map"}

    def test_plain_import_form_flagged(self):
        src = '''
import jax.experimental.shard_map as shmap

def use(f, mesh):
    return shmap.shard_map(f, mesh=mesh)
'''
        fs = run({"dcgan_tpu/parallel/x.py": src}, checks=["DCG003"])
        assert "jax.experimental.shard_map" in {f.key for f in fs}

    def test_docstring_claim_flagged(self):
        src = '"""This backend drives jax.shard_map by hand."""\n'
        fs = run({"dcgan_tpu/parallel/x.py": src}, checks=["DCG003"])
        assert [f.key for f in fs] == ["docstring:jax.shard_map"]

    def test_backend_shim_exempt_and_shim_users_clean(self):
        shim = '''
"""The jax.shard_map compat shim."""
from jax.experimental.shard_map import shard_map as _shard_map
'''
        user = '''
from dcgan_tpu.utils.backend import shard_map

def build(f, mesh, specs):
    return shard_map(f, mesh=mesh, in_specs=specs, out_specs=specs)
'''
        fs = run({"dcgan_tpu/utils/backend.py": shim,
                  "dcgan_tpu/parallel/x.py": user}, checks=["DCG003"])
        assert fs == []

    def test_corrected_shard_map_backend_is_negative_fixture(self):
        # the satellite fix: the real shard_map_backend.py no longer
        # claims the modern API anywhere (docstring included)
        sources = core.collect_sources(
            [core.default_root() + "/dcgan_tpu/parallel"],
            core.default_root())
        fs = core.run_checks(sources, core.Config(inventory={}),
                             checks=["DCG003"])
        assert fs == []


# -- DCG004: parity key inventory --------------------------------------------

class TestKeyInventory:
    TRAINER = "dcgan_tpu/train/trainer.py"  # inside the parity scope

    def test_ungated_key_flagged(self):
        src = 'row = {"perf/new_thing_ms": 1.0}\n'
        fs = run({self.TRAINER: src}, checks=["DCG004"], inventory={})
        assert [f.key for f in fs] == ["perf/new_thing_ms"]
        assert "event-key inventory" in fs[0].message

    def test_declared_and_wildcard_keys_clean(self):
        src = ('row = {"perf/new_thing_ms": 1.0}\n'
               'row2 = {f"sample/{k}": v for k, v in vals.items()}\n')
        inv = {"perf/new_thing_ms": "always", "sample/*": "probe"}
        assert run({self.TRAINER: src}, checks=["DCG004"],
                   inventory=inv) == []

    def test_fstring_prefix_needs_wildcard_entry(self):
        src = 'row[f"perf/compile_ms/{name}"] = ms\n'
        fs = run({self.TRAINER: src}, checks=["DCG004"], inventory={})
        assert [f.key for f in fs] == ["perf/compile_ms/*"]
        assert run({self.TRAINER: src}, checks=["DCG004"],
                   inventory={"perf/compile_ms/*": "aot_warmup"}) == []

    def test_out_of_scope_module_ignored(self):
        src = 'row = {"perf/whatever": 1.0}\n'
        assert run({"dcgan_tpu/evals/x.py": src}, checks=["DCG004"],
                   inventory={}) == []

    def test_serve_namespace_linted_in_serve_modules(self):
        """ISSUE 9: the serving plane's server/__main__ modules are in the
        parity scope and the `serve/` namespace marks key literals — an
        undeclared serve key fails the lint like a trainer key would."""
        src = 'row = {"serve/new_counter": 1.0}\n'
        path = "dcgan_tpu/serve/server.py"
        fs = run({path: src}, checks=["DCG004"], inventory={})
        assert [f.key for f in fs] == ["serve/new_counter"]
        assert run({path: src}, checks=["DCG004"],
                   inventory={"serve/new_counter": "serve entrypoint"}) \
            == []
        # serve literals outside the declared parity modules stay out of
        # scope, same as every other namespace
        assert run({"dcgan_tpu/serve/buckets.py": src}, checks=["DCG004"],
                   inventory={}) == []

    def test_runtime_steptimer_keys_covered(self):
        """The inventory-completeness half the static pass cannot see:
        the keys StepTimer actually produces are all declared."""
        from dcgan_tpu.train.event_keys import EVENT_KEYS
        from dcgan_tpu.utils.profiling import StepTimer

        t = StepTimer(window=4, images_per_step=8)
        t.tick(now=0.0)
        t.note_host(0.001)
        t.tick(now=0.01)
        for key in t.summary():
            assert key_in_inventory(key, EVENT_KEYS), key

    def test_runtime_startup_and_fleet_keys_covered(self):
        from dcgan_tpu.train.coordination import HEALTH_FIELDS, fleet_metrics
        from dcgan_tpu.train.event_keys import EVENT_KEYS
        from dcgan_tpu.utils.profiling import StartupProfile

        sp = StartupProfile()
        with sp.phase("init"):
            pass
        sp.first_step()
        for key in sp.summary():
            assert key_in_inventory(key, EVENT_KEYS), key
        row, _ = fleet_metrics(np.ones((2, len(HEALTH_FIELDS))))
        for key in row:
            assert key_in_inventory(key, EVENT_KEYS), key

    def test_inventory_has_no_stale_trainer_literals(self):
        """Round-trip tightness: every non-wildcard inventory entry that
        names a literal the static pass CAN see is actually still emitted
        somewhere in the scanned modules — a renamed key must retire its
        inventory row, not leave it lying."""
        from dcgan_tpu.analysis.parity import _extract_keys
        from dcgan_tpu.train.event_keys import EVENT_KEYS

        cfg = core.Config()
        sources = core.collect_sources(
            [core.default_root() + "/dcgan_tpu/train",
             core.default_root() + "/dcgan_tpu/serve",
             core.default_root() + "/dcgan_tpu/progressive"],
            core.default_root())
        found = set()
        for sf in sources:
            if sf.path in cfg.parity_modules:
                found.update(k for k, _ in _extract_keys(sf))
        # keys produced through prefix parameters in OTHER modules are
        # pinned by the runtime tests above instead
        runtime_built = {k for k in EVENT_KEYS
                         if k.startswith(("perf/step_ms", "perf/steps_per",
                                          "perf/images_per", "perf/host_ms",
                                          "perf/dispatch_occupancy",
                                          "perf/startup/"))}
        stale = [k for k in EVENT_KEYS
                 if k not in found and k not in runtime_built]
        assert stale == [], f"inventory entries no longer emitted: {stale}"


# -- DCG005: traced-body hygiene ---------------------------------------------

class TestTracedBodyHygiene:
    def test_decorated_jit_with_wall_clock_flagged(self):
        src = '''
import jax, time

@jax.jit
def f(x):
    return x * time.time()
'''
        fs = run({"dcgan_tpu/x.py": src}, checks=["DCG005"])
        assert [f.key for f in fs] == ["f:time.time"]

    def test_passed_by_name_and_lambda_forms(self):
        src = '''
import jax
import numpy as np

def body(x):
    return x + np.random.rand()

g = jax.jit(body)
h = jax.jit(lambda x: x * np.random.rand())
'''
        fs = run({"dcgan_tpu/x.py": src}, checks=["DCG005"])
        assert sorted(f.key for f in fs) == ["<lambda>:np.random.rand",
                                             "body:np.random.rand"]

    def test_shard_map_body_with_host_rng_flagged(self):
        src = '''
import random
from dcgan_tpu.utils.backend import shard_map

def step_body(state, images):
    noise = random.random()
    return state

def build(mesh, specs):
    return shard_map(step_body, mesh=mesh, in_specs=specs,
                     out_specs=specs)
'''
        fs = run({"dcgan_tpu/x.py": src}, checks=["DCG005"])
        assert [f.key for f in fs] == ["step_body:random.random"]

    def test_from_import_form_still_flagged(self):
        src = '''
import jax
from time import time as _t

@jax.jit
def f(x):
    return x * _t()
'''
        fs = run({"dcgan_tpu/x.py": src}, checks=["DCG005"])
        assert [f.key for f in fs] == ["f:time.time"]

    def test_clean_twin_jax_prng_and_untraced_clock(self):
        src = '''
import jax, time

def step_body(state, key):
    z = jax.random.uniform(key, (4,))
    return state, z

g = jax.jit(step_body)

def host_loop():
    return time.time()  # untraced: fine
'''
        assert run({"dcgan_tpu/x.py": src}, checks=["DCG005"]) == []


# -- DCG006: bare filesystem IO ----------------------------------------------

class TestBareIO:
    CKPT = "dcgan_tpu/utils/checkpoint.py"  # inside the IO scope

    def test_bare_replace_flagged(self):
        src = '''
import os

def mark(src, dst):
    os.replace(src, dst)
'''
        fs = run({self.CKPT: src}, checks=["DCG006"])
        assert [f.key for f in fs] == ["os.replace"]

    def test_retry_wrapped_and_fenced_twins_clean(self):
        src = '''
import os
from dcgan_tpu.utils.retry import retry_io

def write(path, payload):
    def _write():
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)
    retry_io(_write, tag="x")

def lam(path):
    retry_io(lambda: os.remove(path), tag="y")

def best_effort(path):
    try:
        os.remove(path)
    except OSError:
        pass
'''
        assert run({self.CKPT: src}, checks=["DCG006"]) == []

    def test_from_import_mutator_still_flagged(self):
        src = '''
from os import replace

def mark(a, b):
    replace(a, b)
'''
        fs = run({self.CKPT: src}, checks=["DCG006"])
        assert [f.key for f in fs] == ["os.replace"]

    def test_reads_exempt_and_scope_respected(self):
        read = '''
def checksum(path):
    with open(path, "rb") as f:
        return len(f.read())
'''
        outside = '''
import os

def anywhere(a, b):
    os.replace(a, b)
'''
        assert run({self.CKPT: read, "dcgan_tpu/evals/x.py": outside},
                   checks=["DCG006"]) == []


# -- suppression + baseline round-trip ---------------------------------------

class TestSuppressionAndBaseline:
    BAD = '''
import jax

def use(f, mesh):
    return jax.shard_map(f, mesh=mesh)
'''

    def test_line_suppression(self):
        suppressed = self.BAD.replace(
            "jax.shard_map(f, mesh=mesh)",
            "jax.shard_map(f, mesh=mesh)  # dcg: disable=DCG003")
        assert run({"dcgan_tpu/x.py": suppressed}, checks=["DCG003"]) == []
        # the wrong ID does not suppress
        wrong = self.BAD.replace(
            "jax.shard_map(f, mesh=mesh)",
            "jax.shard_map(f, mesh=mesh)  # dcg: disable=DCG001")
        assert len(run({"dcgan_tpu/x.py": wrong}, checks=["DCG003"])) == 1

    def test_baseline_round_trip(self, tmp_path):
        fs = run({"dcgan_tpu/x.py": self.BAD}, checks=["DCG003"])
        assert len(fs) == 1
        path = tmp_path / "baseline.jsonl"
        path.write_text("# comment line\n" + "".join(
            json.dumps(f.baseline_entry(why="known legacy")) + "\n"
            for f in fs))
        baseline = core.load_baseline(str(path))
        new, old = core.split_baselined(fs, baseline)
        assert new == [] and len(old) == 1
        # a NEW finding is not absorbed by the old baseline
        two = self.BAD + "\n\ndef more(g, mesh):\n" \
                         "    return jax.shard_map(g, mesh=mesh)\n"
        fs2 = run({"dcgan_tpu/x.py": two}, checks=["DCG003"])
        new2, old2 = core.split_baselined(fs2, baseline)
        assert len(old2) == 1 and len(new2) == 1
        assert new2[0].symbol == "more"

    def test_baseline_requires_why(self, tmp_path):
        path = tmp_path / "b.jsonl"
        path.write_text(json.dumps({"check": "DCG003", "path": "x",
                                    "symbol": "s", "key": "k"}) + "\n")
        with pytest.raises(ValueError, match="why"):
            core.load_baseline(str(path))
        # the --write-baseline draft placeholder is not a justification
        path.write_text(json.dumps({"check": "DCG003", "path": "x",
                                    "symbol": "s", "key": "k",
                                    "why": "TODO: justify"}) + "\n")
        with pytest.raises(ValueError, match="TODO"):
            core.load_baseline(str(path))

    def test_baseline_matching_is_multiset(self):
        """One reviewed entry absorbs one finding: a SECOND violation with
        the same fingerprint (another bare write in the same function)
        still fails the run."""
        src = '''
import os

def mark(a, b, c):
    os.replace(a, b)
    os.replace(b, c)
'''
        fs = run({"dcgan_tpu/utils/checkpoint.py": src}, checks=["DCG006"])
        assert len(fs) == 2 and fs[0].fingerprint() == fs[1].fingerprint()
        entry = fs[0].baseline_entry(why="reviewed once")
        new, old = core.split_baselined(fs, [entry])
        assert len(old) == 1 and len(new) == 1

    def test_unknown_check_id_rejected(self):
        with pytest.raises(ValueError, match="DCG999"):
            run({"dcgan_tpu/x.py": "x = 1\n"}, checks=["DCG999"])


# -- the full-package pin ----------------------------------------------------

class TestPackageClean:
    def test_package_run_is_clean_under_committed_baseline(self):
        root = core.default_root()
        sources = core.collect_sources([root + "/dcgan_tpu"], root)
        findings = core.run_checks(sources, core.Config())
        baseline = core.load_baseline(core.default_baseline_path())
        new, _ = core.split_baselined(findings, baseline)
        assert new == [], "\n".join(
            f"{f.path}:{f.line}: {f.check} {f.message}" for f in new)

    def test_cli_exit_codes(self, tmp_path, capsys):
        from dcgan_tpu.analysis.__main__ import main

        assert main([]) == 0
        capsys.readouterr()
        # with the baseline ignored, the committed exemption resurfaces
        assert main(["--baseline", ""]) == 1
        out = capsys.readouterr().out
        assert "DCG006" in out and "MetricWriter._emit" in out


# -- runtime tripwire --------------------------------------------------------

class TestTripwire:
    def test_offthread_collective_trips_and_dispatch_thread_passes(
            self, monkeypatch):
        monkeypatch.setenv(tripwire.ENV_VAR, "1")
        assert tripwire.maybe_install()
        from dcgan_tpu.train import coordination

        with tripwire.dispatch_scope():
            # dispatch thread: the wrapped entry point passes through
            table = coordination.fleet_health_gather(
                np.zeros(len(coordination.HEALTH_FIELDS), np.float32))
            assert table.shape[0] == 1
            # any other thread: trips
            err = []

            def offthread():
                try:
                    coordination.fleet_health_gather(
                        np.zeros(len(coordination.HEALTH_FIELDS),
                                 np.float32))
                except tripwire.ThreadDisciplineError as e:
                    err.append(e)

            t = threading.Thread(target=offthread)
            t.start()
            t.join()
            assert len(err) == 1
            assert "dispatch thread" in str(err[0])

    def test_silent_outside_dispatch_scope(self):
        """Tools/tests that own their single thread are never tripped:
        without an active scope the wrappers are pass-through from any
        thread."""
        from dcgan_tpu.train import coordination

        results = []

        def offthread():
            results.append(coordination.fleet_health_gather(
                np.zeros(len(coordination.HEALTH_FIELDS), np.float32)))

        t = threading.Thread(target=offthread)
        t.start()
        t.join()
        assert len(results) == 1

    def test_scope_restores_previous_owner(self):
        me = threading.current_thread()
        with tripwire.dispatch_scope():
            assert me in tripwire.dispatch_owners()
            with tripwire.dispatch_scope():
                # re-entrant: still exactly one membership for this thread
                assert me in tripwire.dispatch_owners()
            # the inner exit must not evict the outer scope's ownership
            assert me in tripwire.dispatch_owners()
        # conftest installs but no scope is active between tests
        assert me not in tripwire.dispatch_owners()

    def test_concurrent_replica_scopes_are_independent_owners(self):
        """The serving-fleet shape (ISSUE 19): N dispatch threads each
        inside their own dispatch_scope must all pass the check
        concurrently — one replica entering its scope must never evict
        another's ownership — while an unscoped bystander thread still
        trips."""
        from dcgan_tpu.train import coordination

        n = 3
        entered = threading.Barrier(n + 1)
        release = threading.Event()
        errs, oks = [], []

        def replica(i):
            with tripwire.dispatch_scope():
                entered.wait(timeout=10)
                release.wait(timeout=10)
                try:
                    coordination.fleet_health_gather(
                        np.zeros(len(coordination.HEALTH_FIELDS),
                                 np.float32))
                    oks.append(i)
                except tripwire.ThreadDisciplineError as e:
                    errs.append(e)

        threads = [threading.Thread(target=replica, args=(i,),
                                    name=f"replica-{i}")
                   for i in range(n)]
        for t in threads:
            t.start()
        entered.wait(timeout=10)   # all three scopes active at once
        assert len(tripwire.dispatch_owners()) >= n

        def bystander():
            try:
                coordination.fleet_health_gather(
                    np.zeros(len(coordination.HEALTH_FIELDS), np.float32))
                oks.append("bystander")
            except tripwire.ThreadDisciplineError as e:
                errs.append(e)

        b = threading.Thread(target=bystander, name="bystander")
        b.start()
        b.join(timeout=10)
        release.set()
        for t in threads:
            t.join(timeout=10)
        assert sorted(i for i in oks if i != "bystander") == list(range(n))
        assert "bystander" not in oks
        assert len(errs) == 1 and "dispatch thread" in str(errs[0])
        assert not tripwire.dispatch_owners()

    def test_wrapped_programs_keep_lower(self, monkeypatch):
        """The AOT warmup contract: wrapping pt.* must not hide .lower()."""
        monkeypatch.setenv(tripwire.ENV_VAR, "1")
        tripwire.maybe_install()
        import jax

        from dcgan_tpu.analysis.tripwire import _GuardedFn

        fn = _GuardedFn(jax.jit(lambda x: x + 1), "pt.test")
        assert fn(1) == 2
        lowered = fn.lower(jax.ShapeDtypeStruct((), "int32"))
        assert lowered is not None

    def test_trainer_smoke_zero_trips(self, tmp_path, monkeypatch):
        """A tiny in-process train() under the armed tripwire: the
        default dispatch path records zero trips (the tier-1-wide claim,
        in miniature and in-process)."""
        monkeypatch.setenv(tripwire.ENV_VAR, "1")
        from dcgan_tpu.config import ModelConfig, TrainConfig
        from dcgan_tpu.train.trainer import train

        cfg = TrainConfig(
            model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                              compute_dtype="float32"),
            batch_size=8, tensorboard=False, sample_every_steps=0,
            save_summaries_secs=0.0, log_every_steps=0,
            save_model_secs=1e9,
            checkpoint_dir=str(tmp_path / "ck"),
            sample_dir=str(tmp_path / "sm"))
        state = train(cfg, synthetic_data=True, max_steps=2)
        assert int(np.asarray(state["step"])) == 2


# -- semantic tier (ISSUE 11) ------------------------------------------------
# Fixtures are synthetic jitted programs audited through
# semantic.audit_callable — the spec for what the lowered-program checkers
# resolve, each with a clean twin. The real enumeration runs as the
# tier-1 subprocess pin (tests/test_tools.py), not in-process.

import dataclasses as _dc
import os as _os

import jax as _jax
import jax.numpy as _jnp

from dcgan_tpu.analysis import manifest as mlib
from dcgan_tpu.analysis import semantic


def _audit(fn, args, name="fx::prog", expect_donation=False):
    return semantic.audit_callable(name, fn, args, path="dcgan_tpu/fx.py",
                                   expect_donation=expect_donation)


class TestDonationAliasing:
    """DCG007: donation realized as aliasing, both directions."""

    def test_donated_but_unaliased_flagged(self):
        # the donated dict arg is USED (so it is a live executable input)
        # but no output matches its shape — XLA cannot alias it and every
        # dispatch silently copies
        fn = _jax.jit(lambda s, x: s["a"].sum() + x,
                      donate_argnums=(0,))
        a = _audit(fn, ({"a": _jnp.zeros((4,))}, _jnp.zeros(())),
                   expect_donation=True)
        assert a.donation is not None
        assert a.donation["donated"] == 1 and a.donation["aliased"] == 0
        fs = semantic.check_donation([a])
        assert [f.check for f in fs] == ["DCG007"]
        assert fs[0].key.startswith("unaliased:fx::prog:")
        assert "'a'" in fs[0].key
        assert "input_output_aliases" in fs[0].message

    def test_realized_donation_clean(self):
        fn = _jax.jit(lambda s, x: ({"a": s["a"] + x}, x.sum()),
                      donate_argnums=(0,))
        a = _audit(fn, ({"a": _jnp.zeros((4,))}, _jnp.ones((4,))),
                   expect_donation=True)
        assert a.donation == {"donated": 1, "aliased": 1, "pruned": 0,
                              "unaliased": []}
        assert semantic.check_donation([a]) == []

    def test_pruned_donation_is_not_a_copy_hazard(self):
        # an UNUSED donated arg is pruned from the executable entirely —
        # no input buffer, no copy; classified, not flagged
        fn = _jax.jit(lambda s, x: x * 2.0, donate_argnums=(0,))
        a = _audit(fn, ({"a": _jnp.zeros((4,))}, _jnp.ones((4,))),
                   expect_donation=True)
        assert a.donation["pruned"] == 1 and a.donation["unaliased"] == []
        assert semantic.check_donation([a]) == []

    def test_declared_donor_that_stopped_donating_flagged(self):
        fn = _jax.jit(lambda s: {"a": s["a"] * 2})
        a = _audit(fn, ({"a": _jnp.zeros((4,))},), expect_donation=True)
        assert a.donation is None
        fs = semantic.check_donation([a])
        assert [f.key for f in fs] == ["undonated:fx::prog"]

    def test_undeclared_donor_flagged(self):
        fn = _jax.jit(lambda s: {"a": s["a"] * 2}, donate_argnums=(0,))
        a = _audit(fn, ({"a": _jnp.zeros((4,))},), expect_donation=False)
        fs = semantic.check_donation([a])
        assert [f.key for f in fs] == ["undeclared-donor:fx::prog"]

    def test_non_donor_clean(self):
        a = _audit(_jax.jit(lambda x: x + 1), (_jnp.ones((2,)),))
        assert a.donation is None
        assert semantic.check_donation([a]) == []


class TestProgramManifest:
    """DCG008: manifest round-trip, deliberate-drift detection, the
    transport registry, and the generated DESIGN §6c.1 table."""

    REC = mlib.ProgramRecord(
        name="fx::prog", kind="program", path="dcgan_tpu/fx.py",
        args=("f32[2]",), fingerprint="abcd1234abcd1234",
        collectives={"psum": 2}, donation={"donated": 1, "aliased": 1,
                                           "pruned": 0, "unaliased": []},
        cadence="every step")

    def test_write_read_round_trip(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        with open(path, "w") as f:
            f.write(mlib.dumps([self.REC]))
        assert mlib.load_path(path) == [self.REC]
        # serialization is deterministic: a second dump is byte-identical
        assert mlib.dumps([self.REC]) == mlib.dumps([self.REC])

    def test_census_drift_detected(self):
        committed = [_dc.replace(self.REC, collectives={"psum": 3})]
        fs = mlib.diff([self.REC], committed)
        assert [f.check for f in fs] == ["DCG008"]
        assert fs[0].key == "census:fx::prog"
        assert "psum ×2" in fs[0].message and "psum ×3" in fs[0].message

    def test_fingerprint_and_donation_drift_detected(self):
        committed = [_dc.replace(
            self.REC, fingerprint="ffff0000ffff0000",
            donation={"donated": 1, "aliased": 0, "pruned": 0,
                      "unaliased": ["[0]"]})]
        keys = {f.key for f in mlib.diff([self.REC], committed)}
        assert keys == {"fingerprint:fx::prog", "donation:fx::prog"}

    def test_vanished_and_uncommitted_programs_detected(self):
        other = _dc.replace(self.REC, name="fx::other")
        assert {f.key for f in mlib.diff([self.REC], [other])} == \
            {"missing:fx::other", "uncommitted:fx::prog"}

    def test_identical_records_clean(self):
        assert mlib.diff([self.REC], [_dc.replace(self.REC)]) == []

    def test_missing_manifest_is_a_finding(self, tmp_path):
        fs = semantic.check_manifest([self.REC],
                                     str(tmp_path / "nope.jsonl"))
        assert [f.key for f in fs] == ["manifest-missing"]

    def test_transport_registry_live_and_wrapped(self, monkeypatch):
        assert semantic.check_transports() == []
        from dcgan_tpu.train import coordination

        monkeypatch.setattr(
            coordination, "TRANSPORT_CENSUS",
            {"ghost": ("_allgather_i64", {"all_gather": 1}, "never")})
        keys = {f.key for f in semantic.check_transports()}
        assert keys == {"transport:ghost", "transport-unwrapped:ghost"}

    def test_committed_manifest_carries_the_consensus_transports(self):
        recs = mlib.load_path(mlib.default_manifest_path())
        transports = {r.name for r in recs if r.kind == "transport"}
        # the two PR 4 consensus allgathers, by name — the §6c.1 stream
        assert {"coordination::stop_consensus",
                "coordination::anomaly_consensus"} <= transports
        # and the dispatch surface itself: both backends + serve rungs
        names = {r.name for r in recs}
        assert "gspmd::train_step" in names
        assert "shard_map::train_step" in names
        assert any(n.startswith("serve::sampler@b") for n in names)

    def test_design_stream_table_matches_committed_manifest(self):
        """The §6c.1 dispatch-stream table is GENERATED — the doc block
        between the markers must equal the render from the committed
        manifest, so the doc cannot drift from the programs."""
        recs = mlib.load_path(mlib.default_manifest_path())
        design_path = _os.path.join(core.default_root(), "docs",
                                    "DESIGN.md")
        with open(design_path, encoding="utf-8") as f:
            design = f.read()
        i = design.find(mlib.STREAM_TABLE_BEGIN)
        j = design.find(mlib.STREAM_TABLE_END)
        assert 0 <= i < j, "stream-table markers missing from DESIGN §6c.1"
        block = design[i + len(mlib.STREAM_TABLE_BEGIN):j].strip()
        assert block == mlib.render_stream_table(recs), (
            "DESIGN §6c.1 stream table drifted from the committed "
            "manifest — regenerate with `python -m dcgan_tpu.analysis "
            "--semantic --stream-table` and paste between the markers")


class TestRetraceHazards:
    """DCG009: baked-in consts, weak-typed leaks, warmup coverage."""

    def test_closure_captured_array_flagged(self):
        big = _jnp.arange(100.0)
        a = _audit(_jax.jit(lambda x: x + big.sum()), (_jnp.zeros(()),))
        fs = semantic.check_retrace([a])
        assert len(fs) == 1 and fs[0].check == "DCG009"
        assert fs[0].key.startswith("const:fx::prog:")
        assert "100 elements" in fs[0].message

    def test_argument_passed_array_clean(self):
        a = _audit(_jax.jit(lambda x, big: x + big.sum()),
                   (_jnp.zeros(()), _jnp.arange(100.0)))
        assert semantic.check_retrace([a]) == []

    def test_weak_typed_const_flagged(self):
        w = _jnp.asarray(3.0)  # python float -> weak-typed scalar
        assert w.aval.weak_type
        a = _audit(_jax.jit(lambda x: x * w), (_jnp.ones((2,)),))
        fs = semantic.check_retrace([a])
        assert [f.check for f in fs] == ["DCG009"]
        assert fs[0].key.startswith("weak-const:")

    def test_strong_typed_const_clean(self):
        w = _jnp.float32(3.0)
        a = _audit(_jax.jit(lambda x: x * w), (_jnp.ones((2,)),))
        assert semantic.check_retrace([a]) == []

    def test_warmup_coverage_gap_flagged(self):
        row = semantic.CoverageRow(
            variant="fx", path="dcgan_tpu/fx.py",
            programs=frozenset({"train_step", "sampler"}),
            plan=("train_step",),
            must_cover=frozenset({"train_step", "sampler"}))
        keys = {f.key for f in semantic.check_warmup_coverage([row])}
        assert keys == {"warmup-gap:fx:sampler",
                        "warmup-unplanned:fx:sampler"}

    def test_warmup_full_coverage_clean(self):
        row = semantic.CoverageRow(
            variant="fx", path="dcgan_tpu/fx.py",
            programs=frozenset({"train_step", "sampler", "init"}),
            plan=("train_step", "sampler"),
            must_cover=frozenset({"train_step", "sampler"}))
        assert semantic.check_warmup_coverage([row]) == []

    def test_shape_variant_covers_base_program(self):
        # multi_step planned as "multi_step@k2" still covers the
        # programs-dict entry "multi_step" (base-name match)
        row = semantic.CoverageRow(
            variant="fx", path="dcgan_tpu/fx.py",
            programs=frozenset({"multi_step"}),
            plan=("multi_step@k2",),
            must_cover=frozenset({"multi_step@k2"}))
        assert semantic.check_warmup_coverage([row]) == []


class TestTracedBodySemanticHygiene:
    """DCG010: callbacks, f64 promotion, embedded transfers."""

    def test_host_callback_flagged(self):
        def body(x):
            _jax.debug.print("x = {}", x)
            return x + 1

        a = _audit(_jax.jit(body), (_jnp.ones((2,)),))
        fs = semantic.check_hygiene([a])
        assert len(fs) == 1 and fs[0].check == "DCG010"
        assert fs[0].key.startswith("callback:")

    def test_embedded_device_put_flagged(self):
        a = _audit(_jax.jit(lambda x: _jax.device_put(x) * 2),
                   (_jnp.ones((2,)),))
        fs = semantic.check_hygiene([a])
        assert [f.key for f in fs] == \
            ["transfer:fx::prog:device_put"]

    def test_f64_promotion_flagged(self):
        from jax.experimental import enable_x64

        with enable_x64():
            a = _audit(_jax.jit(lambda x: x.astype(_jnp.float64) * 2),
                       (_jnp.ones((2,), _jnp.float32),))
        fs = semantic.check_hygiene([a])
        assert fs and all(f.key.startswith("f64:") for f in fs)

    def test_plain_program_clean(self):
        a = _audit(_jax.jit(lambda x: x * 2 + 1), (_jnp.ones((2,)),))
        assert semantic.check_hygiene([a]) == []


class TestSemanticBaselineAndChecks:
    """The shared suppression machinery extended to DCG007-010."""

    def test_semantic_finding_round_trips_through_baseline(self):
        fn = _jax.jit(lambda s, x: s["a"].sum() + x, donate_argnums=(0,))
        a = _audit(fn, ({"a": _jnp.zeros((4,))}, _jnp.zeros(())),
                   expect_donation=True)
        fs = semantic.check_donation([a])
        assert len(fs) == 1
        entry = fs[0].baseline_entry(why="fixture: reviewed copy is fine")
        new, old = core.split_baselined(fs, [entry])
        assert new == [] and len(old) == 1
        # multiset semantics: a SECOND identical finding still fails
        new2, old2 = core.split_baselined(fs + fs, [entry])
        assert len(new2) == 1 and len(old2) == 1

    def test_semantic_ids_rejected_by_ast_driver_with_redirect(self):
        with pytest.raises(ValueError, match="--semantic"):
            run({"dcgan_tpu/x.py": "x = 1\n"}, checks=["DCG007"])

    def test_unknown_semantic_id_rejected(self):
        with pytest.raises(ValueError, match="DCG999"):
            semantic.run_semantic(checks=["DCG999"])

    def test_records_from_audits_match_manifest_shape(self):
        a = _audit(_jax.jit(lambda x: x + 1), (_jnp.ones((2,)),))
        recs = semantic.records_from([a])
        by_name = {r.name: r for r in recs}
        assert by_name["fx::prog"].kind == "program"
        assert by_name["fx::prog"].fingerprint == a.fingerprint
        # the declared transports always join the record set
        assert "coordination::stop_consensus" in by_name
        text = mlib.dumps(recs)
        assert mlib.loads(text) == sorted(recs, key=lambda r: r.name)


class TestSpecCoverage:
    """DCG011 (ISSUE 12): every model family's full train state must
    match exactly one sharding-rule row — unmatched and multiply-matched
    paths are findings. The clean case doubles as the committed table's
    coverage proof (tests/test_elastic.py pins the engine semantics)."""

    def test_committed_table_is_clean(self):
        assert semantic.check_spec_coverage() == []

    def test_removed_rule_reports_unmatched(self, monkeypatch):
        from dcgan_tpu.elastic import rules as rmod

        pruned = tuple(r for r in rmod.PARTITION_RULES
                       if r[0] != r"(^|/)proj/w$")
        monkeypatch.setattr(rmod, "PARTITION_RULES", pruned)
        fs = semantic.check_spec_coverage()
        assert fs and all(f.check == "DCG011" for f in fs)
        assert any("spec-unmatched" in f.key and "proj/w" in f.key
                   for f in fs)
        # params, BOTH Adam moments, and the EMA mirror all lose coverage
        keys = "\n".join(f.key for f in fs)
        for stem in ("params/gen/proj/w", "opt/gen/1/0/mu/proj/w",
                     "opt/gen/1/0/nu/proj/w"):
            assert stem in keys

    def test_overlapping_rule_reports_ambiguous(self, monkeypatch):
        from dcgan_tpu.elastic import rules as rmod

        widened = rmod.PARTITION_RULES + (
            (r"(^|/)proj/w$", (None, None)),)
        monkeypatch.setattr(rmod, "PARTITION_RULES", widened)
        fs = semantic.check_spec_coverage()
        assert any(f.check == "DCG011" and "spec-ambiguous" in f.key
                   and "proj/w" in f.key for f in fs)

    def test_prefix_keyed_rule_reports_grad_spec_drift(self, monkeypatch):
        """ISSUE 13: a rule row that keys on the mu/ prefix makes the
        moment resolve differently from the bare-tail GRADIENT spec —
        the reduce-scattered gradient and the shard-local Adam state
        would disagree on layout under zero_stage >= 2, which the
        grad-spec derivation audit must surface."""
        from dcgan_tpu.elastic import rules as rmod

        keyed = ((r"(^|/)mu/proj/w$", rmod.REPLICATED),) \
            + rmod.PARTITION_RULES
        monkeypatch.setattr(rmod, "PARTITION_RULES", keyed)
        fs = semantic.check_spec_coverage()
        assert any(f.check == "DCG011" and "grad-spec-drift" in f.key
                   and "proj/w" in f.key for f in fs)

    def test_dcg011_redirected_from_ast_driver(self):
        with pytest.raises(ValueError, match="--semantic"):
            run({"dcgan_tpu/x.py": "x = 1\n"}, checks=["DCG011"])
