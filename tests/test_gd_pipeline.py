"""Pipelined G/D dispatch (ISSUE 7): GDPipeline fill/drain lifecycle units,
the drain-before-restore rollback hook, the stage-program warmup plan, and
the trainer-level contracts — fused-mode parity (the default dispatch
stream and event values are untouched by the pipeline code), state-tree
invariance across modes (a checkpoint from either mode restores in the
other), and the flight recorder's pipeline phase tag."""

import json
import os

import jax
import pytest

from dcgan_tpu.config import ModelConfig, TrainConfig
from dcgan_tpu.train.gd_pipeline import GDPipeline
from dcgan_tpu.train.rollback import RollbackManager


class _Buf:
    """Stands in for a device-resident fake stack; records its release."""

    def __init__(self, tag):
        self.tag = tag
        self.deleted = False

    def delete(self):
        self.deleted = True


class StubPT:
    """Records the stage-dispatch stream the buffer manager drives."""

    def __init__(self, name="pt"):
        self.name = name
        self.calls = []
        self._n = 0

    def gen_fakes(self, state, key):
        self._n += 1
        buf = _Buf(f"{self.name}-fill{self._n}")
        self.calls.append(("gen_fakes", buf.tag))
        return buf

    def d_update(self, state, images, fakes, key):
        self.calls.append(("d_update", fakes.tag))
        return state, {"d_loss": 0.5}

    def g_update(self, state, key):
        self._n += 1
        buf = _Buf(f"{self.name}-g{self._n}")
        self.calls.append(("g_update", buf.tag))
        return state, buf, {"g_loss": 0.25}


def _key():
    return jax.random.key(0)


class TestGDPipelineLifecycle:
    def test_first_step_fills_then_steady_state_consumes(self):
        """Run start: step 1 dispatches the gen_fakes fill; every later
        step's d_update consumes exactly the stack the PREVIOUS g_update
        produced (staleness 1), with no further fills."""
        pipe, pt = GDPipeline(), StubPT()
        state = {}
        for _ in range(3):
            state, metrics = pipe.step(pt, state, None, _key())
        assert metrics == {"d_loss": 0.5, "g_loss": 0.25}
        assert pipe.fills == 1 and pipe.steps == 3
        consumed = [tag for op, tag in pt.calls if op == "d_update"]
        # step 1 eats the fill; steps 2-3 eat g_update's previous output
        assert consumed == ["pt-fill1", "pt-g2", "pt-g3"]

    def test_checkpoint_boundary_keeps_buffer(self):
        """The buffer lives OUTSIDE the checkpoint pytree: an in-run save
        touches nothing here, so steps around a boundary keep the
        staleness-1 chain with zero extra fills."""
        pipe, pt = GDPipeline(), StubPT()
        state = {}
        state, _ = pipe.step(pt, state, None, _key())
        # <- a periodic checkpoint save happens here: no pipeline API call
        state, _ = pipe.step(pt, state, None, _key())
        assert pipe.fills == 1 and pipe.drains == 0
        assert pipe.primed  # the in-flight stack survived the boundary

    def test_drain_releases_buffer_and_next_step_refills(self):
        """Rollback invalidation: drain drops AND releases the in-flight
        stack; the next step fills again from the (restored) state."""
        pipe, pt = GDPipeline(), StubPT()
        state, _ = pipe.step(pt, {}, None, _key())
        held = next(tag for op, tag in pt.calls if op == "g_update")
        assert pipe.drain("rollback") is True
        assert not pipe.primed and pipe.drains == 1
        assert pipe.last_phase == "drain"
        assert pipe.last_drain_reason == "rollback"
        state, _ = pipe.step(pt, state, None, _key())
        assert pipe.fills == 2
        assert pipe.last_phase == "fill"
        consumed = [tag for op, tag in pt.calls if op == "d_update"]
        refill = [tag for op, tag in pt.calls if op == "gen_fakes"][-1]
        assert consumed[-1] == refill       # never the drained stack
        assert consumed[-1] != held

    def test_drain_calls_device_release(self):
        pipe, pt = GDPipeline(), StubPT()
        pipe.step(pt, {}, None, _key())
        buf = pipe._buf
        pipe.drain("coordinated-stop")
        assert buf.deleted, "drain must release the device buffer"

    def test_drain_on_empty_buffer_is_noop(self):
        """A rollback before the first fill (or a double drain) is free."""
        pipe = GDPipeline()
        assert pipe.drain("rollback") is False
        assert pipe.drains == 0
        pt = StubPT()
        pipe.step(pt, {}, None, _key())
        assert pipe.drain("stop") is True
        assert pipe.drain("stop") is False
        assert pipe.drains == 1

    def test_phase_tags_follow_the_lifecycle(self):
        pipe, pt = GDPipeline(), StubPT()
        assert pipe.last_phase == ""
        pipe.step(pt, {}, None, _key())
        assert pipe.last_phase == "fill"
        pipe.step(pt, {}, None, _key())
        assert pipe.last_phase == "steady"
        pipe.drain("x")
        assert pipe.last_phase == "drain"

    def test_refill_uses_the_current_surface(self):
        """The LR-backoff rollback swaps ParallelTrain surfaces; the
        refill after the swap must dispatch the NEW surface's programs —
        pt binds per call, not at construction."""
        pipe, old, new = GDPipeline(), StubPT("old"), StubPT("new")
        pipe.step(old, {}, None, _key())
        pipe.drain("rollback")
        pipe.step(new, {}, None, _key())
        assert ("gen_fakes", "new-fill1") in new.calls
        consumed = [tag for op, tag in new.calls if op == "d_update"]
        assert consumed == ["new-fill1"]


class TestRollbackDrainHook:
    def _armed(self):
        m = RollbackManager(every=1, max_rollbacks=1)
        m.snapshot(2, {"w": jax.numpy.ones((2,))})
        return m

    def test_on_restore_fires_once_per_consumed_rollback(self):
        m = self._armed()
        drained = []
        m.on_restore = lambda: drained.append(True)
        state, step = m.restore(FloatingPointError("nan at step 3"))
        assert step == 2 and drained == [True]

    def test_on_restore_skipped_when_budget_exhausted(self):
        """An exhausted budget aborts — nothing restores, so the drain
        hook must NOT fire (ordering: after the budget check)."""
        from dcgan_tpu.train.rollback import RollbackExhausted

        m = RollbackManager(every=1, max_rollbacks=0)
        m.snapshot(2, {"w": jax.numpy.ones((2,))})
        drained = []
        m.on_restore = lambda: drained.append(True)
        with pytest.raises(RollbackExhausted):
            m.restore(FloatingPointError("nan"))
        assert drained == []


class TestConfigValidation:
    def _cfg(self, **kw):
        return TrainConfig(model=ModelConfig(output_size=16, gf_dim=8,
                                             df_dim=8), batch_size=16, **kw)

    def test_requires_sequential_update_mode(self):
        with pytest.raises(ValueError, match="sequential"):
            self._cfg(pipeline_gd=True, update_mode="fused")

    def test_rejects_conditional_models(self):
        with pytest.raises(ValueError, match="unconditional"):
            TrainConfig(model=ModelConfig(output_size=16, gf_dim=8,
                                          df_dim=8, num_classes=10),
                        batch_size=16, pipeline_gd=True)

    def test_rejects_multi_step_dispatch(self):
        with pytest.raises(ValueError, match="steps_per_call"):
            self._cfg(pipeline_gd=True, steps_per_call=4)


class TestWarmupPlanStages:
    """--aot_warmup must pre-build exactly what the pipelined loop
    dispatches: the three stage programs instead of the fused step, and
    the LR-backoff prebuild must cover the LR-dependent stages."""

    def _plan_names(self, **kw):
        from dcgan_tpu.parallel import make_mesh, make_parallel_train
        from dcgan_tpu.train import warmup

        cfg = TrainConfig(model=ModelConfig(output_size=16, gf_dim=8,
                                            df_dim=8,
                                            compute_dtype="float32"),
                          batch_size=16, **kw)
        pt = make_parallel_train(cfg, make_mesh(cfg.mesh))
        state = pt.init(jax.random.key(0))
        plan, pt_backoff = warmup.build_warmup_plan(
            cfg, pt, state,
            make_backoff_pt=lambda c: make_parallel_train(c, pt.mesh))
        return [name for name, _, _ in plan], pt_backoff

    def test_pipelined_plan_covers_the_stage_programs(self):
        names, _ = self._plan_names(pipeline_gd=True)
        assert {"gen_fakes", "d_update", "g_update"} <= set(names)
        # the loop never dispatches the fused program under --pipeline_gd
        assert "train_step" not in names

    def test_fused_plan_unchanged(self):
        names, _ = self._plan_names()
        assert "train_step" in names
        assert not any(n.startswith(("gen_fakes", "d_update", "g_update"))
                       for n in names)

    def test_backoff_prebuild_covers_lr_dependent_stages(self):
        names, pt_backoff = self._plan_names(
            pipeline_gd=True, nan_policy="rollback",
            rollback_snapshot_steps=2, rollback_lr_backoff=0.5)
        assert pt_backoff is not None
        assert "d_update@lr_backoff" in names
        assert "g_update@lr_backoff" in names
        # gen_fakes is LR-independent (no optimizer constants): identical
        # HLO to the base program, so it is deliberately NOT re-planned
        assert "gen_fakes@lr_backoff" not in names


class TestShardMapStagesTrace:
    """Regression for the `lax.pcast` latent crash (ISSUE 11 triage):
    this container's jax 0.4.37 predates the VMA type system, so
    steps.py::_zero_metric must fall back to the plain replicated zero
    instead of crashing every shard_map stage-program trace — the tier-1
    suite never lowered these programs on this backend, and the semantic
    analyzer's first enumeration could not even complete."""

    def test_shard_map_pipeline_stages_trace(self):
        import jax.numpy as jnp

        from dcgan_tpu.parallel import make_mesh, make_parallel_train
        from dcgan_tpu.train import warmup

        cfg = TrainConfig(model=ModelConfig(output_size=16, gf_dim=8,
                                            df_dim=8,
                                            compute_dtype="float32"),
                          batch_size=8, backend="shard_map",
                          pipeline_gd=True)
        pt = make_parallel_train(cfg, make_mesh(cfg.mesh))
        state = warmup.state_example(pt)
        img = jax.ShapeDtypeStruct(
            (8, 16, 16, cfg.model.c_dim), jnp.float32)
        fakes = jax.ShapeDtypeStruct(
            (cfg.n_critic, 8, 16, 16, cfg.model.c_dim), jnp.float32)
        key = jax.random.key(0)
        # tracing is the regression surface: pcast raised AttributeError
        # inside the d_update critic scan before any compile
        d = pt.d_update.trace(state, img, fakes, key)
        g = pt.g_update.trace(state, key)
        assert d.jaxpr is not None and g.jaxpr is not None


@pytest.mark.slow
class TestTrainerPipelineContracts:
    """Trainer-level contracts on the real loop (CPU): fused parity,
    state-tree invariance across modes, and the flight recorder tag."""

    def _cfg(self, tmp_path, **kw):
        base = dict(
            model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                              compute_dtype="float32"),
            batch_size=16,
            checkpoint_dir=str(tmp_path / "ckpt"),
            sample_dir=str(tmp_path / "samples"),
            sample_every_steps=0,
            save_summaries_secs=0.0,
            save_model_secs=1e9,
            log_every_steps=0)
        base.update(kw)
        return TrainConfig(**base)

    def _events(self, tmp_path):
        with open(tmp_path / "ckpt" / "events.jsonl") as f:
            return [json.loads(line) for line in f]

    def test_pipelined_scalar_keys_match_fused(self, tmp_path):
        """The pipelined metric row is the fused row's exact key set —
        d_update's discriminator half merged with g_update's g_loss; no
        keys lost, none invented. (Values legitimately differ: staleness-1
        fakes are a different training trajectory.)"""
        from dcgan_tpu.train.trainer import train

        def keys(sub, pipeline):
            cfg = self._cfg(tmp_path / sub, pipeline_gd=pipeline)
            train(cfg, synthetic_data=True, max_steps=4)
            loss_rows = [
                set(e["values"])
                for e in self._events(tmp_path / sub)
                if e["kind"] == "scalars" and "d_loss" in e["values"]]
            assert loss_rows
            return set().union(*loss_rows)

        fused = {k for k in keys("fused", False)
                 if not k.startswith("perf/")}
        pipelined = {k for k in keys("pipelined", True)
                     if not k.startswith("perf/")}
        assert pipelined == fused

    def test_fused_stream_identical_with_pipeline_code_present(self,
                                                               tmp_path):
        """--pipeline_gd off (the default) is reference parity: two
        identical fused runs produce byte-identical event values — the
        pipeline integration added no nondeterminism, no new keys, and no
        dispatch-stream perturbation to the default path."""
        from dcgan_tpu.train.trainer import train

        def run(sub):
            cfg = self._cfg(tmp_path / sub, pipeline_gd=False)
            train(cfg, synthetic_data=True, max_steps=5)
            cleaned = []
            for e in self._events(tmp_path / sub):
                e.pop("time", None)
                if e["kind"] == "scalars":
                    e["values"] = {k: v for k, v in e["values"].items()
                                   if not k.startswith("perf/")}
                cleaned.append(e)
            return cleaned

        a, b = run("a"), run("b")
        assert a == b
        assert not any("pipeline" in k for e in a if e["kind"] == "scalars"
                       for k in e["values"])

    def test_checkpoint_restores_across_modes(self, tmp_path):
        """State-tree invariance: the fake buffer lives OUTSIDE the
        checkpoint pytree, so a fused-mode checkpoint restores under
        --pipeline_gd (and the run refills and completes), and the final
        trees are structurally identical."""
        from dcgan_tpu.train.trainer import train

        cfg_a = self._cfg(tmp_path, pipeline_gd=False)
        state_a = train(cfg_a, synthetic_data=True, max_steps=4)
        assert os.path.isdir(tmp_path / "ckpt" / "4")
        cfg_b = self._cfg(tmp_path, pipeline_gd=True)
        state_b = train(cfg_b, synthetic_data=True, max_steps=6)
        assert int(jax.device_get(state_b["step"])) == 6
        assert (jax.tree_util.tree_structure(state_a)
                == jax.tree_util.tree_structure(state_b))

    def test_flight_recorder_pipeline_tag(self, tmp_path):
        """--pipeline_gd per-step flight records carry the pipeline phase
        tag (a crash dump from a mid-fill hang must say so); fused-mode
        records must NOT gain the key."""
        from dcgan_tpu.train.flight_recorder import read_dump
        from dcgan_tpu.train.trainer import train

        def crash(sub, pipeline):
            cfg = self._cfg(tmp_path / sub, pipeline_gd=pipeline,
                            learning_rate=float("nan"), nan_check_steps=1)
            with pytest.raises(FloatingPointError):
                train(cfg, synthetic_data=True, max_steps=4)
            _, records = read_dump(
                str(tmp_path / sub / "ckpt" / "flight_recorder.jsonl"))
            assert records
            return records

        piped = crash("piped", True)
        assert all(r.get("pipeline") in ("fill", "steady") for r in piped)
        assert piped[0]["pipeline"] == "fill"     # step 1 filled
        fused = crash("fused", False)
        assert all("pipeline" not in r for r in fused)
