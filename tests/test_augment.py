"""DiffAugment (ops/augment.py): per-policy semantics, differentiability,
determinism, and the train-step wiring (arXiv:2006.10738)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcgan_tpu.config import ModelConfig, TrainConfig
from dcgan_tpu.ops.augment import diff_augment, parse_policy
from dcgan_tpu.train import make_train_step

TINY = ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                   compute_dtype="float32")


def imgs(n=4, size=16, seed=0):
    return jnp.asarray(np.tanh(np.random.default_rng(seed).normal(
        size=(n, size, size, 3))).astype(np.float32))


class TestPolicies:
    def test_parse(self):
        assert parse_policy("") == ()
        assert parse_policy("color, cutout") == ("color", "cutout")
        with pytest.raises(ValueError, match="unknown diffaug policy"):
            parse_policy("color,flip")
        with pytest.raises(ValueError, match="unknown diffaug policy"):
            TrainConfig(model=TINY, diffaug="zoom")

    def test_color_changes_values_keeps_shape(self):
        x = imgs()
        y = diff_augment(x, jax.random.key(0), ("color",))
        assert y.shape == x.shape
        assert np.abs(np.asarray(y - x)).max() > 1e-3

    def test_translation_preserves_content_modulo_shift(self):
        """Every output pixel is either zero padding or some input pixel —
        translation moves values, never invents them."""
        x = imgs(n=8)
        y = np.asarray(diff_augment(x, jax.random.key(1), ("translation",)))
        xvals = set(np.round(np.asarray(x).ravel(), 5))
        for v in np.round(y.ravel(), 5)[:2000]:
            assert v == 0.0 or v in xvals

    def test_cutout_zeros_a_block(self):
        x = jnp.ones((4, 16, 16, 3))
        y = np.asarray(diff_augment(x, jax.random.key(2), ("cutout",)))
        zeros = (y == 0).all(axis=-1).sum(axis=(1, 2))
        # an 8x8 hole, possibly clipped by the border: 0 < zeros <= 64
        assert (zeros > 0).all() and (zeros <= 64).all()
        assert np.isin(y, [0.0, 1.0]).all()  # multiply mask, no blending

    def test_deterministic_per_key(self):
        x = imgs()
        pol = ("color", "translation", "cutout")
        a = diff_augment(x, jax.random.key(3), pol)
        b = diff_augment(x, jax.random.key(3), pol)
        c = diff_augment(x, jax.random.key(4), pol)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert np.abs(np.asarray(a - c)).max() > 1e-3

    def test_differentiable(self):
        """Gradients flow through every policy — the property that lets G
        learn through the augmentation."""
        x = imgs()
        pol = ("color", "translation", "cutout")

        def loss(x):
            return jnp.sum(diff_augment(x, jax.random.key(5), pol) ** 2)

        g = np.asarray(jax.grad(loss)(x))
        assert np.isfinite(g).all()
        assert np.abs(g).max() > 0


class TestStepWiring:
    @pytest.mark.slow
    def test_diffaug_step_runs_and_differs(self):
        """The augmented step trains (finite metrics) and takes a different
        trajectory from the unaugmented one."""
        xs, key = imgs(8), jax.random.key(1)
        results = {}
        for spec in ("", "color,translation,cutout"):
            cfg = TrainConfig(model=TINY, batch_size=8, diffaug=spec)
            fns = make_train_step(cfg)
            s, m = jax.jit(fns.train_step)(fns.init(jax.random.key(0)),
                                           xs, key)
            results[spec] = (s, {k: float(v) for k, v in m.items()})
        plain, aug = results[""], results["color,translation,cutout"]
        assert all(np.isfinite(v) for v in aug[1].values())
        assert aug[1]["d_loss"] != plain[1]["d_loss"]

    def test_eval_probe_stays_clean(self):
        """The held-out loss probe never augments — identical across
        policies for the same state."""
        xs, z = imgs(8), jnp.zeros((8, 100))
        vals = []
        for spec in ("", "color"):
            cfg = TrainConfig(model=TINY, batch_size=8, diffaug=spec)
            fns = make_train_step(cfg)
            s = fns.init(jax.random.key(0))
            vals.append(float(jax.jit(fns.eval_losses)(s, xs, z)["d_loss"]))
        np.testing.assert_allclose(vals[0], vals[1], rtol=1e-6)
