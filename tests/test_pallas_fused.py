"""Fused Pallas conv⊕BN⊕act blocks (ops/pallas_fused.py, ISSUE 17) —
interpret-mode execution on the CPU test mesh. The core parity tests
(forward AND custom-VJP gradients against the unfused conv+BN reference)
deliberately carry no `slow` marker: the ISSUE's acceptance gate requires
them in tier-1, so a fused-kernel numerics regression fails the smoke
tier, not just the nightly. Model-integration and shard-path tests ride
the slow tier like the rest of the Pallas suite (tests/test_pallas.py)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from dcgan_tpu.config import ModelConfig
from dcgan_tpu.ops.layers import conv2d_apply, conv2d_init, deconv2d_apply, \
    deconv2d_init
from dcgan_tpu.ops.norm import batch_norm_apply, batch_norm_init
from dcgan_tpu.ops.pallas_fused import (
    _k_tile,
    conv_patches,
    fused_conv_bn_act,
    fused_sites,
    gemm_bias_moments,
    gemm_bias_scale_act,
    kernel_cost,
    w_to_gemm,
)


def _rand(key, shape, dtype=jnp.float32):
    return jax.random.normal(jax.random.key(key), shape, dtype)


def _gbm_ref(p2d, w2d, b, out_dtype=jnp.float32):
    """jnp reference for gemm_bias_moments: f32-accumulated GEMM + bias,
    moments of the value AFTER the compute-dtype cast round-trip (the
    kernel's documented contract — moments describe what the model sees)."""
    u = jnp.dot(p2d.astype(jnp.float32), w2d.astype(jnp.float32)) \
        + b.astype(jnp.float32)[None, :]
    uc = u.astype(out_dtype).astype(jnp.float32)
    return u, jnp.mean(uc, axis=0), jnp.mean(uc * uc, axis=0)


def _act_ref(v, act, leak=0.2):
    if act == "relu":
        return jnp.maximum(v, 0.0)
    if act == "lrelu":
        return jnp.maximum(v, leak * v)
    if act == "tanh":
        return jnp.tanh(v)
    return v


class TestKTile:
    def test_divides_and_bounded(self):
        for n in [1, 7, 25, 150, 512, 800, 1600, 12800, 999]:
            t = _k_tile(n)
            assert n % t == 0 and 1 <= t <= 512

    def test_exact_power_hits_512(self):
        assert _k_tile(4096) == 512


class TestConvPatches:
    """The im2col formulation IS the conv: patches @ w_to_gemm(w) must
    match lax.conv (strided SAME) and lax.conv_transpose (the JAX default
    — no kernel flip) exactly, kernel/stride combinations the models use."""

    @pytest.mark.parametrize("kernel", [4, 5])
    def test_strided_conv(self, kernel):
        x = _rand(0, (2, 8, 8, 6))
        w = _rand(1, (kernel, kernel, 6, 10)) * 0.1
        p2d, (n, ho, wo) = conv_patches(x, kernel, 2, transpose=False)
        got = jnp.dot(p2d, w_to_gemm(w)).reshape(n, ho, wo, 10)
        want = lax.conv_general_dilated(
            x, w, window_strides=(2, 2), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        assert got.shape == want.shape == (2, 4, 4, 10)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("kernel", [4, 5])
    def test_transposed_conv(self, kernel):
        x = _rand(2, (2, 4, 4, 6))
        w = _rand(3, (kernel, kernel, 6, 10)) * 0.1
        p2d, (n, ho, wo) = conv_patches(x, kernel, 2, transpose=True)
        got = jnp.dot(p2d, w_to_gemm(w)).reshape(n, ho, wo, 10)
        want = lax.conv_transpose(
            x, w, strides=(2, 2), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        assert got.shape == want.shape == (2, 8, 8, 10)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


class TestGemmBiasMoments:
    def test_forward_matches_reference(self):
        p2d = _rand(0, (64, 30))
        w2d = _rand(1, (30, 12)) * 0.1
        b = _rand(2, (12,)) * 0.1
        u, mean, msq = gemm_bias_moments(p2d, w2d, b)
        ru, rm, rs = _gbm_ref(p2d, w2d, b)
        assert u.dtype == jnp.float32
        np.testing.assert_allclose(u, ru, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(mean, rm, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(msq, rs, rtol=1e-5, atol=1e-6)

    def test_moments_describe_cast_value(self):
        # under a bf16 policy the moments must match the bf16 round-trip of
        # u, NOT raw-f32 u — bit-parity with the unfused path, which reduces
        # the stored (cast) activation
        p2d = _rand(3, (32, 18))
        w2d = _rand(4, (18, 8))
        b = _rand(5, (8,))
        _, mean, msq = gemm_bias_moments(p2d, w2d, b, jnp.bfloat16)
        _, rm, rs = _gbm_ref(p2d, w2d, b, jnp.bfloat16)
        np.testing.assert_allclose(mean, rm, rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(msq, rs, rtol=1e-6, atol=1e-6)

    def test_grad_matches_autodiff(self):
        p2d = _rand(6, (32, 18))
        w2d = _rand(7, (18, 8)) * 0.1
        b = _rand(8, (8,)) * 0.1
        cu, cm, cs = _rand(9, (32, 8)), _rand(10, (8,)), _rand(11, (8,))

        def via_kernel(p, w, bb):
            u, m, s = gemm_bias_moments(p, w, bb)
            return jnp.sum(u * cu) + jnp.sum(m * cm) + jnp.sum(s * cs)

        def via_ref(p, w, bb):
            u, m, s = _gbm_ref(p, w, bb)
            return jnp.sum(u * cu) + jnp.sum(m * cm) + jnp.sum(s * cs)

        gk = jax.grad(via_kernel, argnums=(0, 1, 2))(p2d, w2d, b)
        gr = jax.grad(via_ref, argnums=(0, 1, 2))(p2d, w2d, b)
        for a, e in zip(gk, gr):
            np.testing.assert_allclose(a, e, rtol=1e-4, atol=1e-4)

    def test_bf16_cotangents_keep_param_dtype(self):
        # regression: the VJP once returned a f32 `db` for a bf16 bias,
        # which promoted the bias's Adam nu leaf to f32 across the step —
        # breaking lax.scan carry dtype invariance and donation aliasing.
        # All three cotangents must come back in their operand's dtype.
        p2d = _rand(12, (16, 10), jnp.bfloat16)
        w2d = _rand(13, (10, 4), jnp.bfloat16)
        b = _rand(14, (4,), jnp.bfloat16)

        def loss(p, w, bb):
            u, m, s = gemm_bias_moments(p, w, bb, jnp.bfloat16)
            return jnp.sum(u) + jnp.sum(m) + jnp.sum(s)

        dp, dw, db = jax.grad(loss, argnums=(0, 1, 2))(p2d, w2d, b)
        assert dp.dtype == jnp.bfloat16
        assert dw.dtype == jnp.bfloat16
        assert db.dtype == jnp.bfloat16


class TestGemmBiasScaleAct:
    @pytest.mark.parametrize("act", ["none", "relu", "lrelu", "tanh"])
    def test_forward_matches_reference(self, act):
        p2d = _rand(0, (32, 18))
        w2d = _rand(1, (18, 8)) * 0.1
        b, scale, shift = _rand(2, (8,)), _rand(3, (8,)), _rand(4, (8,))
        y = gemm_bias_scale_act(p2d, w2d, b, scale, shift, act)
        u = jnp.dot(p2d, w2d) + b[None, :]
        want = _act_ref(u * scale[None, :] + shift[None, :], act)
        np.testing.assert_allclose(y, want, rtol=1e-5, atol=1e-5)

    def test_out_dtype(self):
        p2d = _rand(5, (16, 10))
        w2d = _rand(6, (10, 4))
        b = s = t = jnp.zeros((4,))
        y = gemm_bias_scale_act(p2d, w2d, b, s, t, "relu", 0.2, jnp.bfloat16)
        assert y.dtype == jnp.bfloat16

    @pytest.mark.parametrize("act", ["relu", "lrelu"])
    def test_grad_matches_autodiff(self, act):
        args = (_rand(7, (16, 10)), _rand(8, (10, 4)) * 0.1,
                _rand(9, (4,)), _rand(10, (4,)), _rand(11, (4,)))
        cot = _rand(12, (16, 4))

        def via_kernel(p, w, bb, sc, sh):
            return jnp.sum(gemm_bias_scale_act(p, w, bb, sc, sh, act) * cot)

        def via_ref(p, w, bb, sc, sh):
            u = jnp.dot(p, w) + bb[None, :]
            return jnp.sum(_act_ref(u * sc[None, :] + sh[None, :], act) * cot)

        gk = jax.grad(via_kernel, argnums=(0, 1, 2, 3, 4))(*args)
        gr = jax.grad(via_ref, argnums=(0, 1, 2, 3, 4))(*args)
        for a, e in zip(gk, gr):
            np.testing.assert_allclose(a, e, rtol=1e-4, atol=1e-4)

    def test_bf16_cotangents_keep_param_dtype(self):
        args = tuple(_rand(20 + i, s, jnp.bfloat16) for i, s in
                     enumerate([(16, 10), (10, 4), (4,), (4,), (4,)]))

        def loss(p, w, bb, sc, sh):
            return jnp.sum(gemm_bias_scale_act(p, w, bb, sc, sh, "lrelu",
                                               0.2, jnp.bfloat16)
                           .astype(jnp.float32))

        grads = jax.grad(loss, argnums=(0, 1, 2, 3, 4))(*args)
        assert all(g.dtype == jnp.bfloat16 for g in grads)


def _stage_params(key, in_ch, out_ch, *, transpose, kernel=5):
    k1, k2 = jax.random.split(jax.random.key(key))
    init = deconv2d_init if transpose else conv2d_init
    conv_p = init(k1, in_ch, out_ch, kernel=kernel)
    bn_p, bn_s = batch_norm_init(k2, out_ch)
    return conv_p, bn_p, bn_s


def _unfused_stage(conv_p, bn_p, bn_s, x, *, transpose, act, train,
                   cdt=None, quant=""):
    apply = deconv2d_apply if transpose else conv2d_apply
    y = apply(conv_p, x, compute_dtype=cdt, quant=quant)
    return batch_norm_apply(bn_p, bn_s, y, train=train, act=act)


class TestFusedConvBnAct:
    """The fused stage vs the unfused conv/deconv + batch_norm_apply
    composition the model loops replace — output AND new-state parity,
    both directions, both train modes."""

    @pytest.mark.parametrize("transpose,act", [(False, "lrelu"),
                                               (True, "relu")])
    def test_train_parity(self, transpose, act):
        x = _rand(0, (2, 8, 8, 6))
        conv_p, bn_p, bn_s = _stage_params(1, 6, 10, transpose=transpose)
        y, ns = fused_conv_bn_act(conv_p, bn_p, bn_s, x,
                                  transpose=transpose, kernel=5,
                                  train=True, act=act)
        ry, rns = _unfused_stage(conv_p, bn_p, bn_s, x,
                                 transpose=transpose, act=act, train=True)
        assert y.shape == ry.shape
        np.testing.assert_allclose(y, ry, rtol=1e-4, atol=1e-4)
        for k in ("mean", "var"):
            np.testing.assert_allclose(ns[k], rns[k], rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("transpose,act", [(False, "lrelu"),
                                               (True, "relu")])
    def test_infer_parity_and_state_identity(self, transpose, act):
        x = _rand(2, (2, 8, 8, 6))
        conv_p, bn_p, bn_s = _stage_params(3, 6, 10, transpose=transpose)
        # non-trivial running stats so the single-kernel fold is exercised
        bn_s = {"mean": _rand(4, (10,)) * 0.1,
                "var": 1.0 + 0.1 * jnp.abs(_rand(5, (10,)))}
        y, ns = fused_conv_bn_act(conv_p, bn_p, bn_s, x,
                                  transpose=transpose, kernel=5,
                                  train=False, act=act)
        ry, _ = _unfused_stage(conv_p, bn_p, bn_s, x,
                               transpose=transpose, act=act, train=False)
        np.testing.assert_allclose(y, ry, rtol=1e-4, atol=1e-4)
        assert ns is bn_s  # inference must not touch BN state

    @pytest.mark.parametrize("transpose,act", [(False, "lrelu"),
                                               (True, "relu")])
    def test_train_grads_match_unfused(self, transpose, act):
        x = _rand(6, (2, 8, 8, 6))
        conv_p, bn_p, bn_s = _stage_params(7, 6, 10, transpose=transpose)

        def fused_loss(cp, bp):
            y, _ = fused_conv_bn_act(cp, bp, bn_s, x, transpose=transpose,
                                     kernel=5, train=True, act=act)
            return jnp.sum(y * y)

        def ref_loss(cp, bp):
            y, _ = _unfused_stage(cp, bp, bn_s, x, transpose=transpose,
                                  act=act, train=True)
            return jnp.sum(y * y)

        gf = jax.grad(fused_loss, argnums=(0, 1))(conv_p, bn_p)
        gr = jax.grad(ref_loss, argnums=(0, 1))(conv_p, bn_p)
        # atol floor 2e-3: BN analytically cancels the conv-bias gradient
        # (a bias shift moves the batch mean BN subtracts), so that leaf is
        # pure f32 cancellation noise in BOTH paths; rtol on it is
        # meaningless while the real-signal leaves (w, gamma, beta) are
        # O(0.1..1) and still pinned by it
        jax.tree.map(lambda a, e: np.testing.assert_allclose(
            a, e, rtol=2e-3, atol=2e-3), gf, gr)

    def test_bf16_compute_dtype(self):
        x = _rand(8, (2, 8, 8, 6), jnp.bfloat16)
        conv_p, bn_p, bn_s = _stage_params(9, 6, 10, transpose=False)
        conv_p = jax.tree.map(lambda a: a.astype(jnp.bfloat16), conv_p)
        y, ns = fused_conv_bn_act(conv_p, bn_p, bn_s, x, transpose=False,
                                  kernel=5, train=True, act="lrelu",
                                  compute_dtype=jnp.bfloat16)
        assert y.dtype == jnp.bfloat16
        # BN stat state stays in its stored (f32) dtype under bf16 compute
        assert ns["mean"].dtype == bn_s["mean"].dtype
        ry, _ = _unfused_stage(conv_p, bn_p, bn_s, x, transpose=False,
                               act="lrelu", train=True, cdt=jnp.bfloat16)
        np.testing.assert_allclose(y.astype(jnp.float32),
                                   ry.astype(jnp.float32),
                                   rtol=0.1, atol=0.05)

    def test_fp8_quant_finite_and_close(self):
        # amax scaling means even large operands survive the e4m3 trip
        x = _rand(10, (2, 8, 8, 6)) * 50.0
        conv_p, bn_p, bn_s = _stage_params(11, 6, 10, transpose=False)
        y, _ = fused_conv_bn_act(conv_p, bn_p, bn_s, x, transpose=False,
                                 kernel=5, train=True, act="lrelu",
                                 quant="fp8")
        assert bool(jnp.all(jnp.isfinite(y)))
        ry, _ = _unfused_stage(conv_p, bn_p, bn_s, x, transpose=False,
                               act="lrelu", train=True, quant="fp8")
        np.testing.assert_allclose(y, ry, rtol=0.05, atol=0.05)


class TestConfigValidation:
    def test_requires_use_pallas(self):
        with pytest.raises(ValueError, match="requires use_pallas"):
            ModelConfig(pallas_fused=True)

    def test_dcgan_arch_only(self):
        with pytest.raises(ValueError, match="arch='dcgan' only"):
            ModelConfig(arch="resnet", use_pallas=True, pallas_fused=True)

    def test_rejects_conditional_bn(self):
        with pytest.raises(ValueError, match="conditional_bn"):
            ModelConfig(use_pallas=True, pallas_fused=True,
                        conditional_bn=True, num_classes=4)

    def test_quant_values(self):
        with pytest.raises(ValueError, match="quant"):
            ModelConfig(quant="int4")


class TestCostModel:
    def _cfg64(self):
        return ModelConfig(output_size=64, base_size=4, gf_dim=16, df_dim=16)

    def test_site_census_and_geometry(self):
        cfg = self._cfg64()
        k = cfg.num_up_layers
        sites = fused_sites(cfg, batch=8)
        # interior stages only: G 1..k-1 plus D 1..k-1, boundaries unfused
        assert len(sites) == 2 * (k - 1)
        g1 = next(s for s in sites if s["name"] == "gen/deconv1")
        assert g1["transpose"] and g1["act"] == "relu"
        assert g1["out_res"] == cfg.base_size * 2
        assert g1["m"] == 8 * g1["out_res"] ** 2
        assert g1["k"] == g1["in_ch"] * cfg.kernel_size ** 2
        d1 = next(s for s in sites if s["name"] == "disc/conv1")
        assert not d1["transpose"] and d1["act"] == "lrelu"
        assert d1["in_res"] == cfg.output_size // 2
        assert d1["out_res"] == cfg.output_size // 4

    @pytest.mark.parametrize("train", [True, False])
    def test_parts_conservation(self, train):
        cost = kernel_cost(1024, 150, 32, train=train)
        assert cost["flops"] == sum(cost["flops_parts"].values())
        assert cost["flops_parts"]["gemm"] == 2 * 1024 * 150 * 32
        assert cost["peak_temp_mib"] > 0

    def test_train_costs_more_hbm_than_infer(self):
        tr = kernel_cost(1024, 150, 32, train=True)
        inf = kernel_cost(1024, 150, 32, train=False)
        assert tr["bytes"] > inf["bytes"]

    def test_bf16_shrinks_streaming_bytes(self):
        f32 = kernel_cost(1024, 150, 32, train=False)
        bf16 = kernel_cost(1024, 150, 32, train=False,
                           compute_dtype=jnp.bfloat16)
        assert bf16["bytes"] < f32["bytes"]


# ---------------------------------------------------------------------------
# shard paths + full-model integration: slow tier (multi-device interpret
# runs), same placement as tests/test_pallas.py's integration classes
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestShardPaths:
    def test_axis_name_pmean_matches_global(self):
        from jax.sharding import Mesh, PartitionSpec as P

        from dcgan_tpu.utils.backend import shard_map

        x = _rand(0, (4, 8, 8, 6))
        conv_p, bn_p, bn_s = _stage_params(1, 6, 10, transpose=False)
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))

        def body(xs):
            y, ns = fused_conv_bn_act(conv_p, bn_p, bn_s, xs,
                                      transpose=False, kernel=5, train=True,
                                      act="lrelu", axis_name="data")
            return y, ns

        y, ns = shard_map(body, mesh=mesh, in_specs=P("data"),
                          out_specs=(P("data"), P()), check=False)(x)
        ry, rns = fused_conv_bn_act(conv_p, bn_p, bn_s, x, transpose=False,
                                    kernel=5, train=True, act="lrelu")
        np.testing.assert_allclose(y, ry, rtol=1e-4, atol=1e-4)
        for k in ("mean", "var"):
            np.testing.assert_allclose(ns[k], rns[k], rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("train", [True, False])
    def test_pallas_mesh_matches_global(self, train):
        # the gspmd backend's routing: pallas_call is opaque to GSPMD, so
        # the stage runs per data-shard under a nested shard_map + pmean
        from jax.sharding import Mesh

        x = _rand(2, (4, 8, 8, 6))
        conv_p, bn_p, bn_s = _stage_params(3, 6, 10, transpose=False)
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        y, ns = fused_conv_bn_act(conv_p, bn_p, bn_s, x, transpose=False,
                                  kernel=5, train=train, act="lrelu",
                                  pallas_mesh=mesh)
        ry, rns = fused_conv_bn_act(conv_p, bn_p, bn_s, x, transpose=False,
                                    kernel=5, train=train, act="lrelu")
        np.testing.assert_allclose(y, ry, rtol=1e-4, atol=1e-4)
        if train:
            for k in ("mean", "var"):
                np.testing.assert_allclose(ns[k], rns[k],
                                           rtol=1e-4, atol=1e-5)


@pytest.mark.slow
class TestModelIntegration:
    """ModelConfig.pallas_fused routes every interior stage through the
    fused blocks — whole-net parity against the unfused model."""

    def _cfgs(self):
        # f32 compute: the default bf16 compute dtype rounds the GEMM and
        # conv formulations differently (~bf16-eps output drift), which is
        # precision-policy territory (tests/test_precision.py) — THIS test
        # pins the fused blocks' routing/formulation at full precision
        base = dict(output_size=16, base_size=4, gf_dim=8, df_dim=8, z_dim=8,
                    compute_dtype="float32")
        return (ModelConfig(**base),
                ModelConfig(**base, use_pallas=True, pallas_fused=True))

    def test_generator_parity(self):
        from dcgan_tpu.models.dcgan import generator_apply, generator_init

        plain, fused = self._cfgs()
        params, state = generator_init(jax.random.key(0), plain)
        z = _rand(1, (4, 8))
        for train in (True, False):
            y0, s0 = generator_apply(params, state, z, cfg=plain,
                                     train=train)
            y1, s1 = generator_apply(params, state, z, cfg=fused,
                                     train=train)
            np.testing.assert_allclose(y1, y0, rtol=1e-4, atol=1e-4)
            jax.tree.map(lambda a, e: np.testing.assert_allclose(
                a, e, rtol=1e-4, atol=1e-5), s1, s0)

    def test_discriminator_parity(self):
        from dcgan_tpu.models.dcgan import discriminator_apply, \
            discriminator_init

        plain, fused = self._cfgs()
        params, state = discriminator_init(jax.random.key(2), plain)
        img = jnp.tanh(_rand(3, (4, 16, 16, 3)))
        for train in (True, False):
            p0, l0, s0 = discriminator_apply(params, state, img, cfg=plain,
                                             train=train)
            p1, l1, s1 = discriminator_apply(params, state, img, cfg=fused,
                                             train=train)
            np.testing.assert_allclose(l1, l0, rtol=1e-4, atol=1e-4)
            jax.tree.map(lambda a, e: np.testing.assert_allclose(
                a, e, rtol=1e-4, atol=1e-5), s1, s0)

    def test_generator_grads_parity(self):
        from dcgan_tpu.models.dcgan import generator_apply, generator_init

        plain, fused = self._cfgs()
        params, state = generator_init(jax.random.key(4), plain)
        z = _rand(5, (4, 8))

        def loss(p, cfg):
            y, _ = generator_apply(p, state, z, cfg=cfg, train=True)
            return jnp.mean(y * y)

        g0 = jax.grad(loss)(params, plain)
        g1 = jax.grad(loss)(params, fused)
        jax.tree.map(lambda a, e: np.testing.assert_allclose(
            a, e, rtol=5e-3, atol=5e-4), g1, g0)
