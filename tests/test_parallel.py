"""Multi-device tests on the 8-virtual-CPU mesh: DP equivalence, TP sharding,
synced BN across shards (SURVEY.md §4, §7 phase 4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
from dcgan_tpu.parallel import (
    batch_sharding,
    make_mesh,
    make_parallel_train,
    state_shardings,
)
from dcgan_tpu.train import make_train_step

TINY = ModelConfig(output_size=16, gf_dim=8, df_dim=8, compute_dtype="float32")


def real_batch(n=16, size=16):
    rng = np.random.default_rng(0)
    return jnp.asarray(
        np.tanh(rng.normal(size=(n, size, size, 3))).astype(np.float32))


def max_abs_diff(a, b):
    d = jax.tree_util.tree_map(lambda x, y: float(jnp.max(jnp.abs(x - y))), a, b)
    return max(jax.tree_util.tree_leaves(d))


def test_make_mesh_shapes():
    mesh = make_mesh(MeshConfig())
    assert mesh.devices.size == 8 and mesh.axis_names == ("data", "model")
    mesh2 = make_mesh(MeshConfig(model=2))
    assert mesh2.shape["data"] == 4 and mesh2.shape["model"] == 2


def test_sharding_rules():
    cfg = TrainConfig(model=TINY, batch_size=16, mesh=MeshConfig(model=2))
    mesh = make_mesh(cfg.mesh)
    fns = make_train_step(cfg)
    shapes = jax.eval_shape(fns.init, jax.random.key(0))
    sh = state_shardings(shapes, mesh)
    # conv kernels shard out-channels on "model"
    assert sh["params"]["disc"]["conv0"]["w"].spec == P(None, None, None, "model")
    # generator projection shards its wide output dim
    assert sh["params"]["gen"]["proj"]["w"].spec == P(None, "model")
    # head shards its wide input dim
    assert sh["params"]["disc"]["head"]["w"].spec == P("model", None)
    # BN params/stats and biases replicated
    assert sh["params"]["gen"]["bn0"]["scale"].spec == P()
    assert sh["bn"]["disc"]["bn1"]["mean"].spec == P()
    # Adam moments mirror the param rules (mu lives under the same leaf paths)
    opt_leaves = jax.tree_util.tree_leaves_with_path(sh["opt"]["gen"])
    conv_mu = [s for path, s in opt_leaves
               if any(getattr(p, "key", None) == "deconv1" for p in path)
               and any(getattr(p, "key", None) == "w" for p in path)]
    assert conv_mu and all(s.spec == P(None, None, None, "model")
                           for s in conv_mu)


def test_spatial_sharding_rules():
    """spatial=True: weights replicate; images shard over (batch, height) —
    the sequence-parallel analogue for conv data (SURVEY.md §2.5)."""
    cfg = TrainConfig(model=TINY, batch_size=16,
                      mesh=MeshConfig(model=2, spatial=True))
    mesh = make_mesh(cfg.mesh)
    fns = make_train_step(cfg)
    shapes = jax.eval_shape(fns.init, jax.random.key(0))
    sh = state_shardings(shapes, mesh, spatial=True)
    for s in jax.tree_util.tree_leaves(sh):
        assert s.spec == P()
    img_sh = batch_sharding(mesh, 4, spatial=True)
    assert img_sh.spec == P("data", "model", None, None)
    # non-image inputs never spatial-shard
    assert batch_sharding(mesh, 2, spatial=True).spec == P("data", None)


# dp8 is the one sharded-equivalence case kept in the smoke tier; the other
# partitionings are slow-tier (each is a fresh multi-device compile)
@pytest.mark.parametrize(
    "mesh_cfg,model,conditional",
    [pytest.param(MeshConfig(), TINY, False, id="dp8"),
     pytest.param(MeshConfig(model=2), TINY, False, id="dp4xtp2",
                  marks=pytest.mark.slow),
     pytest.param(MeshConfig(model=2, spatial=True), TINY, False,
                  id="dp4xsp2", marks=pytest.mark.slow),
     # spatial + attention + use_pallas: the gspmd gate now admits this
     # cell by dropping only the BN half of the flag (bn_pallas=False) and
     # routing the attention through ring x flash
     # (ops/pallas_attention.py::ring_flash_attention); the single-device
     # reference runs flash + fused-BN — both exact, so they must agree
     pytest.param(MeshConfig(model=2, spatial=True), "ring-flash", False,
                  id="dp4xsp2-ringflash", marks=pytest.mark.slow),
     # pure-DP gspmd + flash attention + XLA BN (r5): the flash kernels
     # run per data-shard through attn_apply's pallas_mesh nested
     # shard_map — the rev-2 attention presets' execution form; must
     # match the single-device step exactly like every other partitioning
     pytest.param(MeshConfig(), "dp-flash", False, id="dp8-flash",
                  marks=pytest.mark.slow),
     pytest.param(MeshConfig(shard_opt=True), TINY, False, id="dp8-zero1",
                  marks=pytest.mark.slow),
     pytest.param(MeshConfig(), "cbn", True, id="dp8-cbn",
                  marks=pytest.mark.slow)])
def test_sharded_step_matches_single_device(mesh_cfg, model, conditional):
    """The sharded SPMD step must be numerically equivalent to the unsharded
    step — data parallelism here is synchronous (one global batch, global BN
    moments, all-reduced grads), NOT the reference's async Hogwild
    (SURVEY.md §2.5). The cbn case additionally covers the conditional-BN
    per-example [K, C] table gather (labels batch-sharded, tables
    replicated)."""
    import dataclasses

    if model == "cbn":
        model = dataclasses.replace(TINY, num_classes=4, conditional_bn=True)
    elif model == "ring-flash":
        model = dataclasses.replace(TINY, attn_res=8, use_pallas=True)
    elif model == "dp-flash":
        model = dataclasses.replace(TINY, attn_res=8, use_pallas=True,
                                    bn_pallas=False)
    cfg = TrainConfig(model=model, batch_size=16, mesh=mesh_cfg)
    xs, key = real_batch(), jax.random.key(3)
    labels = (jnp.asarray(np.arange(16) % model.num_classes),) \
        if conditional else ()

    fns = make_train_step(cfg)
    s_ref, m_ref = jax.jit(fns.train_step)(fns.init(jax.random.key(0)), xs,
                                           key, *labels)

    pt = make_parallel_train(cfg)
    s_par = pt.init(jax.random.key(0))
    s_par, m_par = pt.step(s_par, xs, key, *labels)

    # Losses agree tightly; params loosely — Adam's first step is
    # ~±lr·sign(grad), so f32 reduction-order noise between partitionings can
    # flip near-zero gradient signs, bounding the diff by ~2·lr = 4e-4.
    np.testing.assert_allclose(float(m_par["d_loss"]), float(m_ref["d_loss"]),
                               rtol=1e-5)
    np.testing.assert_allclose(float(m_par["g_loss"]), float(m_ref["g_loss"]),
                               rtol=1e-5)
    assert max_abs_diff(s_ref["params"], jax.device_get(s_par["params"])) \
        <= 2 * cfg.learning_rate + 1e-5


@pytest.mark.slow
def test_multi_step_matches_sequential_steps():
    """multi_step (K steps as one lax.scan program, one dispatch) must equal
    K individual step() calls fed the same keys and batches."""
    cfg = TrainConfig(model=TINY, batch_size=16)
    xs = real_batch()
    keys = jax.random.split(jax.random.key(7), 3)

    pt = make_parallel_train(cfg)
    s_seq = pt.init(jax.random.key(0))
    for i in range(3):
        s_seq, m_seq = pt.step(s_seq, xs, keys[i])

    s_scan = pt.init(jax.random.key(0))
    imgs_k = jnp.broadcast_to(xs, (3,) + xs.shape)
    s_scan, m_scan = pt.multi_step(s_scan, imgs_k, keys)

    assert int(s_scan["step"]) == 3
    np.testing.assert_allclose(float(m_scan["d_loss"]),
                               float(m_seq["d_loss"]), rtol=1e-4)
    # scanned and unrolled programs fuse differently; f32 reduction-order
    # noise can flip near-zero Adam update signs, ~±2*lr per step (same
    # bound as test_sharded_step_matches_single_device)
    assert max_abs_diff(jax.device_get(s_seq["params"]),
                        jax.device_get(s_scan["params"])) \
        <= 3 * 2 * cfg.learning_rate + 1e-5


@pytest.mark.slow
def test_sharded_state_placement():
    cfg = TrainConfig(model=TINY, batch_size=16, mesh=MeshConfig(model=2))
    pt = make_parallel_train(cfg)
    state = pt.init(jax.random.key(0))
    w = state["params"]["gen"]["proj"]["w"]
    # physically sharded over the model axis: each shard holds 1/2 the columns
    shard_shapes = {tuple(s.data.shape) for s in w.addressable_shards}
    assert shard_shapes == {(w.shape[0], w.shape[1] // 2)}
    step = state["step"]
    assert all(s.data.shape == () for s in step.addressable_shards)


@pytest.mark.slow
def test_sharded_sample_and_multiple_steps():
    cfg = TrainConfig(model=TINY, batch_size=16)
    pt = make_parallel_train(cfg)
    s = pt.init(jax.random.key(0))
    xs = real_batch()
    for i in range(3):
        s, m = pt.step(s, xs, jax.random.fold_in(jax.random.key(1), i))
    assert int(s["step"]) == 3
    z = jax.random.uniform(jax.random.key(2), (16, 100), minval=-1, maxval=1)
    img = pt.sample(s, z)
    assert img.shape == (16, 16, 16, 3)


@pytest.mark.slow
def test_conditional_sharded_step():
    cfg = TrainConfig(
        model=ModelConfig(output_size=16, gf_dim=8, df_dim=8, num_classes=4,
                          compute_dtype="float32"),
        batch_size=16)
    pt = make_parallel_train(cfg)
    s = pt.init(jax.random.key(0))
    y = jnp.arange(16) % 4
    s, m = pt.step(s, real_batch(), jax.random.key(1), y)
    assert np.isfinite(float(m["d_loss"]))


@pytest.mark.slow
def test_zero1_opt_state_sharding():
    """shard_opt=True (ZeRO-1, arXiv:2004.13336): Adam moments shard over
    the data axis; params/BN stay on their usual rules; the physical shards
    each hold 1/8 of the moment tensors."""
    cfg = TrainConfig(model=TINY, batch_size=16,
                      mesh=MeshConfig(shard_opt=True))
    mesh = make_mesh(cfg.mesh)
    fns = make_train_step(cfg)
    shapes = jax.eval_shape(fns.init, jax.random.key(0))
    sh = state_shardings(shapes, mesh, shard_opt=True)
    # conv-kernel moments [5,5,in,out]: data axis lands on the first dim it
    # divides; params themselves stay replicated (pure DP mesh)
    leaves = jax.tree_util.tree_leaves_with_path(sh["opt"]["disc"])
    kernel_specs = [s.spec for path, s in leaves
                    if any(getattr(p, "key", None) == "conv1" for p in path)
                    and any(getattr(p, "key", None) == "w" for p in path)]
    assert kernel_specs and all("data" in tuple(s) for s in kernel_specs)
    # params never pick up the data axis (ZeRO-1 shards only optimizer state)
    assert "data" not in tuple(sh["params"]["disc"]["conv1"]["w"].spec)

    pt = make_parallel_train(cfg, mesh)
    state = pt.init(jax.random.key(0))
    # [0] is the grad-clip slot (EmptyState), [1] the adam chain
    mu_w = state["opt"]["disc"][1][0].mu["conv1"]["w"]
    full = int(np.prod(mu_w.shape))
    shard_sizes = {int(np.prod(s.data.shape))
                   for s in mu_w.addressable_shards}
    assert shard_sizes == {full // 8}
    # and the params stayed fully replicated on every device
    w = state["params"]["disc"]["conv1"]["w"]
    assert all(s.data.shape == w.shape for s in w.addressable_shards)


def test_zero1_rejected_for_shard_map_backend():
    with pytest.raises(ValueError, match="shard_opt"):
        TrainConfig(model=TINY, backend="shard_map",
                    mesh=MeshConfig(shard_opt=True))


@pytest.mark.slow
def test_g_ema_sharded():
    """ema_gen mirrors the generator param paths, so the TP sharding rules
    hit it automatically; one sharded step keeps it consistent."""
    cfg = TrainConfig(model=TINY, batch_size=16, g_ema_decay=0.999,
                      mesh=MeshConfig(model=2))
    mesh = make_mesh(cfg.mesh)
    fns = make_train_step(cfg)
    shapes = jax.eval_shape(fns.init, jax.random.key(0))
    sh = state_shardings(shapes, mesh)
    assert sh["ema_gen"]["proj"]["w"].spec == P(None, "model")

    pt = make_parallel_train(cfg, mesh)
    s = pt.init(jax.random.key(0))
    s, m = pt.step(s, real_batch(), jax.random.key(1))
    assert np.isfinite(float(m["g_loss"]))
    z = jax.random.uniform(jax.random.key(2), (16, 100), minval=-1, maxval=1)
    assert pt.sample(s, z).shape == (16, 16, 16, 3)


@pytest.mark.slow
def test_wgan_gp_sharded():
    """Grad-of-grad through the GSPMD-sharded mesh (SURVEY.md §7 hard part c)."""
    cfg = TrainConfig(model=TINY, batch_size=16, loss="wgan-gp")
    pt = make_parallel_train(cfg)
    s = pt.init(jax.random.key(0))
    s, m = pt.step(s, real_batch(), jax.random.key(1))
    assert np.isfinite(float(m["gp"]))
