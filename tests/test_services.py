"""Host-services executor (train/services.py): ordering, drop-oldest
backpressure, error propagation to the dispatch thread, drain barriers, and
the inline escape hatch — plus the trainer-level contracts: lag-by-one NaN
attribution and async/inline metrics-JSONL equivalence (ISSUE 2)."""

import json
import threading
import time

import pytest

from dcgan_tpu.train.services import (
    HostServices,
    InlineServices,
    ServiceError,
    make_services,
)


class TestHostServices:
    def test_tasks_run_in_order(self):
        svc = HostServices()
        seen = []
        for i in range(10):
            svc.submit(lambda i=i: seen.append(i))
        svc.drain()
        assert seen == list(range(10))
        assert svc.completed == 10 and svc.dropped == 0
        svc.close()

    def test_drop_oldest_backpressure(self):
        """A full queue discards the OLDEST droppable task — training (the
        submitter) never blocks on telemetry."""
        svc = HostServices(max_queue=4)
        gate = threading.Event()
        done = []
        svc.submit(gate.wait, droppable=False)  # wedge the worker
        deadline = time.monotonic() + 5.0
        while svc.pending() > 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        for i in range(10):    # 4-deep queue: only the newest survive
            svc.submit(lambda i=i: done.append(i))
        assert svc.dropped > 0
        gate.set()
        svc.drain()
        # the survivors are the most recent submissions, still in order
        assert done == sorted(done)
        assert done[-1] == 9 and len(done) <= 4
        svc.close()

    def test_non_droppable_never_dropped(self):
        svc = HostServices(max_queue=2)
        gate = threading.Event()
        done = []
        svc.submit(gate.wait, droppable=False)
        # wait for the worker to pick the wedge up so it never occupies a
        # queue slot the assertions below reason about
        deadline = time.monotonic() + 5.0
        while svc.pending() > 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        svc.submit(lambda: done.append("keep1"), droppable=False)
        svc.submit(lambda: done.append("keep2"), droppable=False)
        # a third non-droppable submit must wait for space, so release the
        # worker from another thread shortly
        threading.Timer(0.2, gate.set).start()
        svc.submit(lambda: done.append("keep3"), droppable=False)
        svc.drain()
        assert done == ["keep1", "keep2", "keep3"]
        assert svc.dropped == 0
        svc.close()

    def test_worker_error_propagates_to_dispatch_thread(self):
        svc = HostServices()
        svc.submit(lambda: (_ for _ in ()).throw(OSError("disk full")),
                   tag="scalars")
        deadline = time.monotonic() + 5.0
        with pytest.raises(ServiceError, match="scalars"):
            while time.monotonic() < deadline:
                svc.raise_if_failed()
                time.sleep(0.01)
        # a failed executor refuses further work instead of hiding it
        assert svc.submit(lambda: None) is False
        with pytest.raises(ServiceError):
            svc.drain()

    def test_drain_is_a_barrier(self):
        svc = HostServices()
        done = []
        svc.submit(lambda: (time.sleep(0.2), done.append(1)))
        svc.drain()
        assert done == [1]  # not merely queued: executed
        svc.close()

    def test_close_idempotent(self):
        svc = HostServices()
        svc.submit(lambda: None)
        svc.close()
        svc.close()
        assert svc.submit(lambda: None) is False

    def test_factory(self):
        assert isinstance(make_services(True), HostServices)
        assert isinstance(make_services(False), InlineServices)

    def test_inline_runs_immediately_on_caller(self):
        svc = InlineServices()
        tid = []
        svc.submit(lambda: tid.append(threading.get_ident()))
        assert tid == [threading.get_ident()]  # same thread, already done
        with pytest.raises(RuntimeError):
            svc.submit(lambda: (_ for _ in ()).throw(RuntimeError("now")))


@pytest.mark.slow
class TestTrainerServiceContracts:
    """The trainer-level behaviors the executor exists for, on the real
    loop (JAX_PLATFORMS=cpu via conftest)."""

    def _cfg(self, tmp_path, **kw):
        from dcgan_tpu.config import ModelConfig, TrainConfig

        base = dict(
            model=ModelConfig(output_size=16, gf_dim=8, df_dim=8,
                              compute_dtype="float32"),
            batch_size=16,
            checkpoint_dir=str(tmp_path / "ckpt"),
            sample_dir=str(tmp_path / "samples"),
            sample_grid=(2, 2),
            sample_size=4,
            sample_every_steps=3,
            save_summaries_secs=0.0,   # every loop check fires
            save_model_secs=1e9,       # only the final forced save
            log_every_steps=0)
        base.update(kw)
        return TrainConfig(**base)

    def test_lag_by_one_nan_gate_attribution(self, tmp_path):
        """Async mode materializes step N's metrics during step N+1, but a
        NaN must still abort naming step N — the record carries its own
        step, not the loop's current one."""
        from dcgan_tpu.train.trainer import train

        cfg = self._cfg(tmp_path, sample_every_steps=0,
                        learning_rate=float("nan"), nan_check_steps=1,
                        async_services=True)
        with pytest.raises(FloatingPointError, match="step 1"):
            train(cfg, synthetic_data=True, max_steps=5)

    def test_final_step_nan_still_gated(self, tmp_path):
        """The lag-by-one window flushes after the loop: a NaN in the very
        last step cannot slip out un-gated."""
        from dcgan_tpu.train.trainer import train

        cfg = self._cfg(tmp_path, sample_every_steps=0,
                        learning_rate=float("nan"), nan_check_steps=1,
                        async_services=True)
        with pytest.raises(FloatingPointError, match="step 1"):
            train(cfg, synthetic_data=True, max_steps=1)

    def test_async_and_inline_write_identical_metric_values(self, tmp_path):
        """--async_services=false is the escape hatch: same seed, same
        steps -> the deterministic event content (kinds, steps, metric
        values) matches the async run's; only wall-clock fields (`time`,
        perf/*) may differ."""
        from dcgan_tpu.train.trainer import train

        def run(sub, async_services):
            cfg = self._cfg(tmp_path / sub, activation_summary_steps=5,
                            async_services=async_services)
            train(cfg, synthetic_data=True, max_steps=7)
            events = [json.loads(line) for line in
                      open(tmp_path / sub / "ckpt" / "events.jsonl")]
            cleaned = []
            for e in events:
                e.pop("time", None)
                if e["kind"] == "scalars":
                    e["values"] = {k: v for k, v in e["values"].items()
                                   if not k.startswith("perf/")}
                if e["kind"] == "image":
                    import os
                    e["path"] = os.path.basename(e["path"])
                cleaned.append(e)
            # the async writer may interleave event ORDER across kinds
            # (scalars lag one step); compare kind-keyed sorted streams
            return sorted(cleaned, key=lambda e: (e["kind"], e["step"],
                                                  json.dumps(e,
                                                             sort_keys=True)))

        a = run("async", True)
        b = run("inline", False)
        assert a == b

    def test_drain_on_checkpoint(self, tmp_path, monkeypatch):
        """A periodic checkpoint save forces the telemetry queue empty —
        events ordered before the checkpoint are durable before training
        proceeds past it."""
        from dcgan_tpu.train import trainer as trainer_mod
        from dcgan_tpu.train import services as services_mod

        drained = []
        orig_drain = services_mod.HostServices.drain

        def spy_drain(self, timeout=None):
            drained.append(self.pending())
            return orig_drain(self, timeout)

        monkeypatch.setattr(services_mod.HostServices, "drain", spy_drain)
        cfg = self._cfg(tmp_path, sample_every_steps=0,
                        save_model_secs=0.0,  # every maybe_save fires
                        async_services=True)
        trainer_mod.train(cfg, synthetic_data=True, max_steps=3)
        # one drain per periodic save + the exit barrier; after each the
        # queue really is empty
        assert len(drained) >= 3
