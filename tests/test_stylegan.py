"""StyleGAN2-lite family (models/stylegan.py, arch="stylegan"): mapping
network + modulated convolutions + skip tRGB through the same entry
points, machinery, and parallel layers as the other stacks; paired with
the norm-free residual critic (models/resnet.py)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
from dcgan_tpu.models.dcgan import (
    discriminator_apply,
    gan_init,
    generator_apply,
    sampler_apply,
)

TINY = ModelConfig(arch="stylegan", output_size=16, gf_dim=8, df_dim=8,
                   compute_dtype="float32")


def _z(n=4, dim=100, seed=0):
    return jnp.asarray(np.random.default_rng(seed).uniform(
        -1, 1, (n, dim)), jnp.float32)


def real_batch(n=16, size=16):
    rng = np.random.default_rng(0)
    return jnp.asarray(
        np.tanh(rng.normal(size=(n, size, size, 3))).astype(np.float32))


class TestShapes:
    def test_generator_shapes_range_and_statelessness(self):
        params, bn = gan_init(jax.random.key(0), TINY)
        img, new_state = generator_apply(params["gen"], bn["gen"], _z(),
                                         cfg=TINY, train=True)
        assert img.shape == (4, 16, 16, 3)
        assert img.dtype == jnp.float32
        assert float(jnp.abs(img).max()) <= 1.0
        # no BN anywhere: the generator state is empty, train has no effect
        assert bn["gen"] == {} and new_state == {}
        img_eval, _ = generator_apply(params["gen"], bn["gen"], _z(),
                                      cfg=TINY, train=False)
        np.testing.assert_array_equal(np.asarray(img), np.asarray(img_eval))

    def test_discriminator_is_resnet_critic(self):
        """arch='stylegan' pairs G with the norm-free residual critic —
        same param names, no BN state."""
        params, bn = gan_init(jax.random.key(0), TINY)
        assert bn["disc"] == {}
        assert "head" in params["disc"] and "b0_conv1" in params["disc"]
        x = real_batch(4)
        prob, logit, _ = discriminator_apply(params["disc"], bn["disc"], x,
                                             cfg=TINY, train=True)
        assert logit.shape == (4, 1) and logit.dtype == jnp.float32

    def test_styles_modulate_output(self):
        """Different z must produce different images THROUGH the styles:
        the synthesis input is a constant, so z only enters via w."""
        params, bn = gan_init(jax.random.key(0), TINY)
        a, _ = generator_apply(params["gen"], bn["gen"], _z(seed=1),
                               cfg=TINY, train=True)
        b, _ = generator_apply(params["gen"], bn["gen"], _z(seed=2),
                               cfg=TINY, train=True)
        assert float(jnp.abs(a - b).max()) > 1e-3

    def test_demodulation_normalizes_weight_scale(self):
        """Demodulated convs are invariant to the conv-weight SCALE (the
        property that stands in for equalized LR): scaling every b*_conv*
        kernel leaves the pre-tRGB features unchanged."""
        params, bn = gan_init(jax.random.key(0), TINY)
        cap1, cap2 = {}, {}
        generator_apply(params["gen"], bn["gen"], _z(), cfg=TINY,
                        train=True, capture=cap1)
        scaled = {k: ({**v, "w": v["w"] * 7.0}
                      if k.endswith(("_conv1", "_conv2")) else v)
                  if isinstance(v, dict) else v
                  for k, v in params["gen"].items()}
        generator_apply(scaled, bn["gen"], _z(), cfg=TINY, train=True,
                        capture=cap2)
        # h-features equal up to f32 noise (biases unscaled, demod exact)
        for k in ("h1", "h2"):
            np.testing.assert_allclose(np.asarray(cap1[k]),
                                       np.asarray(cap2[k]),
                                       rtol=1e-4, atol=1e-5)

    def test_conditional_z_concat(self):
        cfg = dataclasses.replace(TINY, num_classes=4)
        params, bn = gan_init(jax.random.key(0), cfg)
        labels = jnp.asarray([0, 1, 2, 3])
        img, _ = generator_apply(params["gen"], bn["gen"], _z(), cfg=cfg,
                                 train=True, labels=labels)
        assert img.shape == (4, 16, 16, 3)
        img2, _ = generator_apply(params["gen"], bn["gen"], _z(), cfg=cfg,
                                  train=True, labels=jnp.asarray([1, 0, 3, 2]))
        assert float(jnp.abs(img - img2).max()) > 1e-4
        with pytest.raises(ValueError, match="labels"):
            generator_apply(params["gen"], bn["gen"], _z(), cfg=cfg,
                            train=True)

    def test_capture_channels(self):
        params, bn = gan_init(jax.random.key(0), TINY)
        cap = {}
        generator_apply(params["gen"], bn["gen"], _z(), cfg=TINY,
                        train=True, capture=cap)
        assert "w" in cap and "h1" in cap and "h3" in cap  # k=2 stages + out

    def test_validation_rejects_unwired_composition(self):
        with pytest.raises(ValueError, match="conditional"):
            ModelConfig(arch="stylegan", output_size=16, num_classes=2,
                        conditional_bn=True)
        with pytest.raises(ValueError, match="attention"):
            ModelConfig(arch="stylegan", output_size=16, attn_res=8)
        with pytest.raises(ValueError, match="spectral_norm"):
            ModelConfig(arch="stylegan", output_size=16, spectral_norm="gd")


class TestTraining:
    @pytest.mark.slow
    def test_train_step_sample_and_r1(self):
        """The stylegan64 recipe at tiny scale: R1-regularized BCE with the
        SN critic, EMA sampling — one jitted step, finite metrics, moving
        params."""
        from dcgan_tpu.train import make_train_step

        cfg = TrainConfig(
            model=dataclasses.replace(TINY, spectral_norm="d"),
            batch_size=16, r1_gamma=10.0, g_ema_decay=0.99)
        fns = make_train_step(cfg)
        s = fns.init(jax.random.key(0))
        step = jax.jit(fns.train_step)
        for i in range(3):
            s, m = step(s, real_batch(), jax.random.fold_in(
                jax.random.key(1), i))
        assert int(s["step"]) == 3
        for k, v in m.items():
            assert np.isfinite(float(v)), (k, v)
        assert "r1" in m
        img = fns.sample(s, _z(16))
        assert img.shape == (16, 16, 16, 3)
        assert float(jnp.abs(img).max()) <= 1.0

    @pytest.mark.slow
    def test_sharded_step_matches_single_device(self):
        """Same equivalence contract as the other families: the dp8-sharded
        stylegan step equals the single-device step (no BN means no
        moment-sync subtlety — pure data-parallel grads)."""
        from dcgan_tpu.parallel import make_parallel_train
        from dcgan_tpu.train import make_train_step

        cfg = TrainConfig(model=TINY, batch_size=16, mesh=MeshConfig())
        xs, key = real_batch(), jax.random.key(3)
        fns = make_train_step(cfg)
        s_ref, m_ref = jax.jit(fns.train_step)(fns.init(jax.random.key(0)),
                                               xs, key)
        pt = make_parallel_train(cfg)
        s_par, m_par = pt.step(pt.init(jax.random.key(0)), xs, key)
        np.testing.assert_allclose(float(m_par["d_loss"]),
                                   float(m_ref["d_loss"]), rtol=1e-5)
        np.testing.assert_allclose(float(m_par["g_loss"]),
                                   float(m_ref["g_loss"]), rtol=1e-5)
        diff = jax.tree_util.tree_map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))),
            s_ref["params"], jax.device_get(s_par["params"]))
        assert max(jax.tree_util.tree_leaves(diff)) \
            <= 2 * cfg.learning_rate + 1e-5

    @pytest.mark.slow
    def test_sampler_and_checkpoint_roundtrip(self, tmp_path):
        """sampler_apply goes through the same dispatch; checkpoint the
        state and restore it under a generate-style config."""
        from dcgan_tpu.train import make_train_step
        from dcgan_tpu.utils.checkpoint import Checkpointer

        cfg = TrainConfig(model=TINY, batch_size=8,
                          checkpoint_dir=str(tmp_path))
        fns = make_train_step(cfg)
        s = fns.init(jax.random.key(0))
        s, _ = jax.jit(fns.train_step)(s, real_batch(8), jax.random.key(1))
        ck = Checkpointer(str(tmp_path))
        ck.save(1, s, force=True)
        ck.wait()
        restored = Checkpointer(str(tmp_path)).restore_latest(
            jax.eval_shape(fns.init, jax.random.key(0)))
        img = sampler_apply(restored["params"]["gen"], restored["bn"]["gen"],
                            _z(8), cfg=TINY)
        assert img.shape == (8, 16, 16, 3)

    def test_preset_exists(self):
        from dcgan_tpu.presets import get_preset

        cfg = get_preset("stylegan64")
        assert cfg.model.arch == "stylegan"
        assert cfg.r1_gamma > 0 and cfg.r1_interval == 16
        assert cfg.g_ema_decay == 0.999
