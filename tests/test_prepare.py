"""Dataset-preparation CLI tests: image folder -> TFRecord shards -> pipeline.

Closes the loop the reference left open (its preprocessing was commented out,
image_input.py:123-132; records were assumed to pre-exist): images written
with PIL round-trip through prepare.convert into the exact batches the
training pipeline yields.
"""

import json
import os

import numpy as np
import pytest
from PIL import Image

from dcgan_tpu.data import DataConfig, make_dataset
from dcgan_tpu.data.prepare import build_parser, convert, load_and_preprocess


def write_images(d, n, size=(20, 28), value=None, ext=".png"):
    os.makedirs(d, exist_ok=True)
    rng = np.random.default_rng(0)
    for i in range(n):
        arr = (np.full(size + (3,), value, np.uint8) if value is not None
               else rng.integers(0, 256, size + (3,), dtype=np.uint8))
        Image.fromarray(arr).save(os.path.join(d, f"img_{i:03d}{ext}"))


class TestPreprocess:
    def test_center_crop_and_resize(self, tmp_path):
        # 40x60 image, distinctive center: crop 20 keeps the middle block
        arr = np.zeros((60, 40, 3), np.uint8)
        arr[20:40, 10:30] = 200
        p = str(tmp_path / "x.png")
        Image.fromarray(arr).save(p)
        out = load_and_preprocess(p, image_size=16, crop_size=20)
        assert out.shape == (16, 16, 3) and out.dtype == np.float64
        np.testing.assert_allclose(out, 200.0)  # all center pixels

    def test_small_image_upscaled_before_crop(self, tmp_path):
        p = str(tmp_path / "tiny.png")
        Image.fromarray(np.full((8, 8, 3), 50, np.uint8)).save(p)
        out = load_and_preprocess(p, image_size=16, crop_size=108)
        assert out.shape == (16, 16, 3)
        np.testing.assert_allclose(out, 50.0)

    def test_crop_disabled(self, tmp_path):
        p = str(tmp_path / "x.png")
        Image.fromarray(np.full((10, 30, 3), 7, np.uint8)).save(p)
        out = load_and_preprocess(p, image_size=8, crop_size=0)
        assert out.shape == (8, 8, 3)


class TestConvert:
    def test_roundtrip_through_pipeline(self, tmp_path):
        src, dst = str(tmp_path / "src"), str(tmp_path / "dst")
        write_images(src, 12, value=128)
        paths = convert(src, dst, image_size=16, crop_size=0, num_shards=3)
        assert len(paths) == 3
        manifest = json.load(open(os.path.join(dst, "dataset.json")))
        assert manifest["num_examples"] == 12
        # default wire format is uint8 (VERDICT r3 #6: the float64 parity
        # format is input-bound at chip rates; it stays available behind
        # record_dtype="float64" — exercised by the roundtrip test below)
        assert manifest["record_dtype"] == "uint8"

        cfg = DataConfig(data_dir=dst, image_size=16, batch_size=4,
                         min_after_dequeue=4, n_threads=2, seed=0,
                         normalize=True, loop=False, record_dtype="uint8")
        batch = next(iter(make_dataset(cfg)))
        assert batch.shape == (4, 16, 16, 3)
        # 128/127.5 - 1 ~ 0.0039 after [-1,1] normalization
        np.testing.assert_allclose(np.asarray(batch), 128 / 127.5 - 1,
                                   atol=1e-5)

    def test_uint8_records(self, tmp_path):
        src, dst = str(tmp_path / "src"), str(tmp_path / "dst")
        write_images(src, 4, value=64)
        convert(src, dst, image_size=8, crop_size=0, num_shards=1,
                record_dtype="uint8")
        cfg = DataConfig(data_dir=dst, image_size=8, batch_size=2,
                         record_dtype="uint8", min_after_dequeue=2,
                         n_threads=1, seed=0, normalize=True, loop=False)
        batch = next(iter(make_dataset(cfg)))
        np.testing.assert_allclose(np.asarray(batch), 64 / 127.5 - 1,
                                   atol=1e-5)

    def test_labeled_subdirs(self, tmp_path):
        src, dst = str(tmp_path / "src"), str(tmp_path / "dst")
        write_images(os.path.join(src, "cat"), 3, value=10)
        write_images(os.path.join(src, "dog"), 3, value=250)
        convert(src, dst, image_size=8, crop_size=0, num_shards=1,
                labeled=True)
        manifest = json.load(open(os.path.join(dst, "dataset.json")))
        assert manifest["classes"] == ["cat", "dog"]
        cfg = DataConfig(data_dir=dst, image_size=8, batch_size=6,
                         min_after_dequeue=2, n_threads=1, seed=0,
                         normalize=False, loop=False, label_feature="label",
                         record_dtype="uint8")
        imgs, labels = next(iter(make_dataset(cfg)))
        labels = np.asarray(labels)
        imgs = np.asarray(imgs)
        assert set(labels.tolist()) == {0, 1}
        # label/image pairing survives shuffling: cat=10, dog=250
        for img, lbl in zip(imgs, labels):
            np.testing.assert_allclose(img, 10.0 if lbl == 0 else 250.0)

    def test_refuses_stale_shards_without_overwrite(self, tmp_path):
        src, dst = str(tmp_path / "src"), str(tmp_path / "dst")
        write_images(src, 4)
        convert(src, dst, image_size=8, crop_size=0, num_shards=4)
        with pytest.raises(ValueError, match="--overwrite"):
            convert(src, dst, image_size=8, crop_size=0, num_shards=2)
        paths = convert(src, dst, image_size=8, crop_size=0, num_shards=2,
                        overwrite=True)
        shards = [f for f in os.listdir(dst) if f.endswith(".tfrecord")]
        assert len(paths) == 2 and len(shards) == 2  # no stale shard-0000[23]

    def test_shards_are_class_mixed(self, tmp_path):
        """Seeded shuffle before sharding: with 2 classes and 2 shards, each
        shard must hold both classes (class-major order would give one each,
        starving a 2-process run of the other class entirely)."""
        from dcgan_tpu.data.example_proto import parse_example
        from dcgan_tpu.data.tfrecord import read_tfrecords

        src, dst = str(tmp_path / "src"), str(tmp_path / "dst")
        write_images(os.path.join(src, "cat"), 8, value=10)
        write_images(os.path.join(src, "dog"), 8, value=250)
        paths = convert(src, dst, image_size=8, crop_size=0, num_shards=2,
                        labeled=True)
        for p in paths:
            labels = {parse_example(r)["label"][0]
                      for r in read_tfrecords(p)}
            assert labels == {0, 1}, (p, labels)

    def test_manifest_mismatch_rejected_by_pipeline(self, tmp_path):
        src, dst = str(tmp_path / "src"), str(tmp_path / "dst")
        write_images(src, 4)
        convert(src, dst, image_size=8, crop_size=0, num_shards=1,
                record_dtype="uint8")
        cfg = DataConfig(data_dir=dst, image_size=16, batch_size=2,
                         record_dtype="float64", min_after_dequeue=2,
                         n_threads=1, seed=0, loop=False)
        with pytest.raises(ValueError, match="dataset was prepared with"):
            next(iter(make_dataset(cfg)))
        cfg_lbl = DataConfig(data_dir=dst, image_size=8, batch_size=2,
                             record_dtype="uint8", min_after_dequeue=2,
                             n_threads=1, seed=0, loop=False,
                             label_feature="label")
        with pytest.raises(ValueError, match="prepared unlabeled"):
            next(iter(make_dataset(cfg_lbl)))

    def test_empty_dir_rejected(self, tmp_path):
        src = str(tmp_path / "empty")
        os.makedirs(src)
        with pytest.raises(ValueError, match="no images"):
            convert(src, str(tmp_path / "out"))

    def test_labeled_without_subdirs_rejected(self, tmp_path):
        src = str(tmp_path / "flat")
        write_images(src, 2)
        with pytest.raises(ValueError, match="subdirectories"):
            convert(src, str(tmp_path / "out"), labeled=True)


class TestCifar10:
    def _write_fake_batches(self, d, per_batch=10):
        """Fabricate the cifar-10-batches-py layout: uint8 rows in
        R,G,B-plane order + labels. Pixel value encodes the label so the
        image<->label pairing is checkable after shuffling."""
        import pickle

        os.makedirs(d, exist_ok=True)
        names = [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]
        for bi, name in enumerate(names):
            data = np.zeros((per_batch, 3072), np.uint8)
            labels = [(bi + j) % 10 for j in range(per_batch)]
            for j, lbl in enumerate(labels):
                data[j] = 20 * lbl + 5  # constant image per label
            with open(os.path.join(d, name), "wb") as f:
                pickle.dump({b"data": data, b"labels": labels}, f)

    def test_convert_and_roundtrip(self, tmp_path):
        from dcgan_tpu.data.prepare import convert_cifar10

        src, dst = str(tmp_path / "cifar"), str(tmp_path / "recs")
        self._write_fake_batches(src)
        paths = convert_cifar10(src, dst, num_shards=2)
        assert len(paths) == 2
        manifest = json.load(open(os.path.join(dst, "dataset.json")))
        assert manifest["num_examples"] == 50  # 5 train batches x 10
        assert manifest["classes"][0] == "airplane"
        assert manifest["record_dtype"] == "uint8"

        cfg = DataConfig(data_dir=dst, image_size=32, batch_size=10,
                         record_dtype="uint8", min_after_dequeue=4,
                         n_threads=1, seed=0, normalize=False, loop=False,
                         label_feature="label")
        imgs, labels = next(iter(make_dataset(cfg)))
        for img, lbl in zip(np.asarray(imgs), np.asarray(labels)):
            np.testing.assert_allclose(img, 20 * int(lbl) + 5)

    def test_cli_defaults_uint8_for_cifar(self, tmp_path):
        """main() resolves record_dtype per mode: cifar10 -> uint8 unless
        the user asks otherwise (float64 would be 8x larger for no reason)."""
        from dcgan_tpu.data.prepare import main

        src = str(tmp_path / "cifar")
        self._write_fake_batches(src)
        out = str(tmp_path / "recs")
        main(["--input_dir", src, "--output_dir", out, "--cifar10",
              "--num_shards", "1"])
        manifest = json.load(open(os.path.join(out, "dataset.json")))
        assert manifest["record_dtype"] == "uint8"
        assert manifest["image_size"] == 32

    def test_test_split_and_missing_files(self, tmp_path):
        from dcgan_tpu.data.prepare import convert_cifar10

        src = str(tmp_path / "cifar")
        self._write_fake_batches(src)
        convert_cifar10(src, str(tmp_path / "t"), split="test", num_shards=1)
        manifest = json.load(open(str(tmp_path / "t" / "dataset.json")))
        assert manifest["num_examples"] == 10
        with pytest.raises(FileNotFoundError, match="data_batch"):
            convert_cifar10(str(tmp_path / "empty"), str(tmp_path / "o"))


def test_cli_parser():
    args = build_parser().parse_args(
        ["--input_dir", "a", "--output_dir", "b", "--record_dtype", "uint8",
         "--labeled", "--crop_size", "0"])
    assert args.record_dtype == "uint8" and args.labeled
    with pytest.raises(SystemExit):
        build_parser().parse_args(["--input_dir", "a"])  # output_dir required
