"""Protocol tier units (ISSUE 14): the lockstep simulator engine, the
virtual-trainer scenarios, the committed protocol lock, the DCG013
divergence lint, and the DCG014/015 stale-exemption audits — all
in-process (the simulator needs no subprocesses by design). The live
2-process replay proof is tools/chaos_drill.py mh-sigterm-stop (pinned
via test_tools.py), which compares a real trainer's logged collective
sequence against the committed simulator schedule."""

import json
import os

import pytest

from dcgan_tpu.analysis import core, protocol, simulate

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COMMITTED_LOCK = os.path.join(REPO, "dcgan_tpu", "analysis",
                              "protocol.lock.jsonl")


@pytest.fixture(scope="module")
def lattice():
    """One shared exploration — deterministic by construction, so every
    test reads the same result set (~2 s once per module)."""
    return simulate.run_lattice()


@pytest.fixture(scope="module")
def lock_rows(lattice):
    return protocol.rows_from_results(lattice)


def _scenario(lattice, config, fault):
    for r in lattice:
        if r.knobs.name == config and r.fault.name == fault:
            return r
    raise AssertionError(f"lattice has no {config}/{fault} scenario")


# -- three-way transport registry ---------------------------------------------

class TestTransportRegistry:
    """A transport added to any one of {simulator shims, runtime
    tripwire, census declarations} must fail loudly in the other two."""

    def test_three_way_set_equality(self):
        from dcgan_tpu.analysis import tripwire
        from dcgan_tpu.train import coordination

        sim = set(simulate.SIM_TRANSPORTS)
        wrapped = set(tripwire.WRAPPED_TRANSPORTS)
        census = {row[0] for row in
                  coordination.TRANSPORT_CENSUS.values()}
        assert sim == wrapped
        assert census <= sim
        # every simulated transport is a real coordination callable
        for name in sim:
            assert callable(getattr(coordination, name))

    def test_verify_passes_on_the_real_registries(self):
        simulate.verify_transport_registry()

    def test_new_sim_transport_fails(self, monkeypatch):
        monkeypatch.setattr(simulate, "SIM_TRANSPORTS",
                            simulate.SIM_TRANSPORTS + ("_allgather_i64",))
        with pytest.raises(simulate.SimProtocolError, match="diverged"):
            simulate.verify_transport_registry()

    def test_new_wrapped_transport_fails(self, monkeypatch):
        from dcgan_tpu.analysis import tripwire

        monkeypatch.setattr(
            tripwire, "WRAPPED_TRANSPORTS",
            tripwire.WRAPPED_TRANSPORTS + ("_allgather_i64",))
        with pytest.raises(simulate.SimProtocolError, match="diverged"):
            simulate.verify_transport_registry()

    def test_new_census_transport_fails(self, monkeypatch):
        from dcgan_tpu.train import coordination

        census = dict(coordination.TRANSPORT_CENSUS)
        census["new_thing"] = ("_allgather_i64", {"all_gather": 1}, "x")
        monkeypatch.setattr(coordination, "TRANSPORT_CENSUS", census)
        with pytest.raises(simulate.SimProtocolError,
                           match="does not drive"):
            simulate.verify_transport_registry()

    def test_every_census_op_appears_in_the_lock(self, lock_rows):
        """Coverage, not just registration: the explored lattice must
        actually EXERCISE every declared logical transport (plus the
        warmup barrier) somewhere."""
        from dcgan_tpu.train import coordination

        entries = set()
        for row in lock_rows:
            if row["kind"] == "scenario":
                for e in row["schedule"]:
                    entries.add(e.split(":", 1)[-1].split("@")[0])
        for op in coordination.TRANSPORT_CENSUS:
            assert op in entries, f"lattice never exercises {op}"
        assert "warmup_barrier" in entries


# -- the rendezvous engine ----------------------------------------------------

def _knobs(**kw):
    kw.setdefault("name", "fixture")
    return simulate.Knobs(**kw)


class TestEngine:
    def test_consensus_values_cross_processes(self):
        """The real anomaly_consensus over the rendezvous transport: a
        verdict local to process 1 reaches process 0's branch."""
        def program(mesh, pid, knobs, plan):
            from dcgan_tpu.train import coordination

            with mesh.phase("anomaly_consensus@1"):
                bad, who = coordination.anomaly_consensus(pid == 1)
            return f"verdict:{bad}:{who}"

        r = simulate.run_scenario(_knobs(), simulate.Fault.make("clean"),
                                  program=program)
        assert r.statuses == ["done", "done"]
        assert r.outcomes == ["verdict:True:[1]"] * 2
        assert r.schedules[0] == r.schedules[1] \
            == ["ag:anomaly_consensus@1"]

    def test_asymmetric_branch_is_a_dcg012_deadlock(self):
        """The canonical single-branch asymmetry: one process runs an
        extra consensus its peer never enters — caught, attributed."""
        def program(mesh, pid, knobs, plan):
            from dcgan_tpu.train import coordination

            if pid == 0:
                with mesh.phase("anomaly_consensus@1"):
                    coordination.anomaly_consensus(False)
            mesh.collective("save", "final_save@1")
            return "completed@1"

        r = simulate.run_scenario(_knobs(), simulate.Fault.make("clean"),
                                  program=program)
        assert r.failure is not None
        assert not r.terminated
        findings = protocol.audit_results([r])
        assert [f.check for f in findings] == ["DCG012"]
        assert findings[0].key == "deadlock"
        assert "anomaly_consensus" in findings[0].message

    def test_early_exit_leaves_peer_blocked(self):
        """A process that returns while its peer enters a collective is
        the other deadlock shape (the PR 3-era one-host-save bug)."""
        def program(mesh, pid, knobs, plan):
            if pid == 1:
                return "completed@0"  # exits without the final save
            mesh.collective("save", "final_save@0")
            return "completed@0"

        r = simulate.run_scenario(_knobs(), simulate.Fault.make("clean"),
                                  program=program)
        assert r.failure is not None and r.failure["absent"] == [1]
        findings = protocol.audit_results([r])
        assert findings and findings[0].key == "deadlock"

    def test_hang_with_watchdog_resolves_as_trip(self):
        def program(mesh, pid, knobs, plan):
            mesh.collective("prog", "train_step@0")
            if pid == 1:
                mesh.hang("hang@1")
            mesh.collective("prog", "train_step@1")
            return "completed@2"

        r = simulate.run_scenario(_knobs(collective_timeout_secs=8.0),
                                  simulate.Fault.make("clean"),
                                  program=program)
        assert r.terminated
        assert r.statuses == ["trip", "hung"]
        assert r.outcomes[0] == "watchdog-trip:train_step@1"
        assert protocol.audit_results([r]) == []

    def test_hang_without_watchdog_is_a_finding(self):
        def program(mesh, pid, knobs, plan):
            if pid == 1:
                mesh.hang("hang@0")
            mesh.collective("prog", "train_step@0")
            return "completed@1"

        r = simulate.run_scenario(_knobs(), simulate.Fault.make("clean"),
                                  program=program)
        assert not r.terminated
        findings = protocol.audit_results([r])
        assert findings and findings[0].key == "deadlock"

    def test_single_process_collectives_complete_immediately(self):
        def program(mesh, pid, knobs, plan):
            from dcgan_tpu.train import coordination

            with mesh.phase("anomaly_consensus@1"):
                bad, _ = coordination.anomaly_consensus(False)
            mesh.collective("save", "final_save@1")
            return f"completed:{bad}"

        r = simulate.run_scenario(_knobs(n_proc=1),
                                  simulate.Fault.make("clean"),
                                  program=program)
        assert r.statuses == ["done"]
        # single-process consensus takes the local branch: no collective
        # entry for it, exactly the real transport's contract
        assert r.schedules[0] == ["save:final_save@1"]

    def test_repeated_tags_rendezvous_by_occurrence(self):
        """A replayed window re-enters the same (op, tag) — occurrence
        counting must pair the n-th entries, not wedge."""
        def program(mesh, pid, knobs, plan):
            for _ in range(2):
                mesh.collective("prog", "train_step@2")
            return "completed@2"

        r = simulate.run_scenario(_knobs(), simulate.Fault.make("clean"),
                                  program=program)
        assert r.statuses == ["done", "done"]
        assert r.schedules[0] == ["prog:train_step@2"] * 2


# -- virtual-trainer scenarios ------------------------------------------------

class TestVirtualTrainer:
    def test_drill_scenario_lockstep_stop(self, lattice):
        r = _scenario(lattice, *protocol.DRILL_REPLAY_SCENARIO)
        assert r.statuses == ["done", "done"]
        assert r.outcomes == ["stopped@3", "stopped@3"]
        assert r.schedules[0] == r.schedules[1]
        assert protocol.coord_ops(r.schedules[0]) == \
            ["stop_consensus"] * 4

    def test_nan_on_one_host_aborts_both(self, lattice):
        r = _scenario(lattice, "consensus-abort", "nan@p1@2")
        assert r.outcomes == ["aborted@2", "aborted@2"]
        assert "ag:anomaly_consensus@2" in r.schedules[0]
        # abort exits never reach the final collective save
        assert not any(e.startswith("save:") for e in r.schedules[0])

    def test_rollback_delete_protocol_in_schedule(self, lattice):
        r = _scenario(lattice, "rollback", "nan@p0@2")
        assert r.outcomes == ["completed@6", "completed@6"]
        sched = r.schedules[0]
        # the real delete_steps_after's verdict allgather, at the
        # consensus-agreed rollback point
        assert any(e.startswith("ag:rollback_delete@") for e in sched)

    def test_transient_io_fault_is_protocol_invisible(self, lattice):
        """retry_io absorbs the injected ckpt-delete OSError: the
        schedule must be IDENTICAL to the same fault without the IO
        error — transient host IO never perturbs the collective
        stream."""
        plain = _scenario(lattice, "rollback", "nan@p0@2")
        with_io = _scenario(lattice, "rollback",
                            "nan@p0@2+io-ckpt-delete")
        assert with_io.schedules == plain.schedules
        assert with_io.outcomes == plain.outcomes

    def test_pipeline_drain_precedes_rollback_delete(self, lattice):
        """ISSUE 7's ordering contract, audited: the pipelined-stack
        drain (parked on RollbackManager.on_restore) runs before the
        delete barrier."""
        r = _scenario(lattice, "pipelined-zero2", "nan@p0@2")
        sched = r.schedules[0]
        drain = sched.index("local:pipeline-drain:rollback")
        delete = next(i for i, e in enumerate(sched)
                      if e.startswith("ag:rollback_delete@"))
        assert drain < delete
        # pipelined dispatch refills after the drain: gen_fakes again
        assert sum(1 for e in sched
                   if e.startswith("prog:gen_fakes")) >= 2

    def test_zero_stage_names_the_program_stream(self, lattice):
        r = _scenario(lattice, "pipelined-zero2", "clean")
        assert any(e.startswith("prog:d_update@zero2@")
                   for e in r.schedules[0])
        r3 = _scenario(lattice, "zero3-fleet", "clean")
        assert any(e.startswith("prog:train_step@zero3@")
                   for e in r3.schedules[0])

    @pytest.mark.parametrize("config,decision", [
        ("rollback", "direct"), ("zero3-fleet", "device"),
        ("elastic-host-restore", "host")])
    def test_elastic_restore_decision_variants(self, lattice, config,
                                               decision):
        r = _scenario(lattice, config, "clean")
        assert r.schedules[0][0] == f"local:restore:{decision}"
        assert r.schedules[0] == r.schedules[-1]

    def test_warmup_barrier_in_armed_configs(self, lattice):
        r = _scenario(lattice, "rollback", "clean")
        assert "bar:warmup_barrier@start" in r.schedules[0]
        r2 = _scenario(lattice, "drill-defaults", "clean")
        assert not any(e.startswith("bar:") for e in r2.schedules[0])

    def test_fleet_health_cadence(self, lattice):
        r = _scenario(lattice, "zero3-fleet", "clean")
        health = [e for e in r.schedules[0]
                  if e.startswith("ag:fleet_health@")]
        assert health == [f"ag:fleet_health@{s}" for s in (2, 4, 6)]

    def test_local_stop_config_has_no_stop_consensus(self, lattice):
        r = _scenario(lattice, "local-stop", "clean")
        assert not any("stop_consensus" in e for e in r.schedules[0])

    def test_hang_fault_watchdog_prefix_rule(self, lattice):
        r = _scenario(lattice, "watchdog", "hang@p0@1")
        assert r.terminated
        assert r.statuses[0] == "hung" and r.statuses[1] == "trip"
        hung = r.schedules[0][:-1]  # strip the hang marker
        assert r.schedules[1][:len(hung)] == hung
        assert protocol.audit_results([r]) == []

    def test_rollback_budget_exhaustion_aborts_symmetrically(self):
        k = _knobs(name="exhaust", nan_policy="rollback",
                   nan_check_steps=1, max_rollbacks=1,
                   rollback_snapshot_steps=2, total_steps=6)
        f = simulate.Fault.make("nan-twice", {0: {"nan_at_step": 2},
                                              1: {"nan_at_step": 4}})
        r = simulate.run_scenario(k, f)
        assert r.statuses == ["done", "done"]
        assert r.outcomes[0] == r.outcomes[1]
        assert r.outcomes[0].startswith("aborted@")
        assert protocol.audit_results([r]) == []


# -- the lattice + lock -------------------------------------------------------

class TestLatticeAndLock:
    def test_acceptance_coverage(self, lattice):
        """ISSUE 14 acceptance: >= 4 knob configs x >= 6 fault
        interleavings each, every interleaving terminating, zero audit
        findings."""
        per = {}
        for r in lattice:
            per[r.knobs.name] = per.get(r.knobs.name, 0) + 1
            assert r.terminated, f"{r.knobs.name}/{r.fault.name}"
        assert len([c for c, n in per.items() if n >= 6]) >= 4
        assert protocol.audit_results(lattice) == []

    def test_committed_lock_matches_a_fresh_exploration(self, lock_rows):
        """Byte-reproducibility AND drift, at full strength: a fresh
        exploration serialized must equal the committed lock exactly."""
        with open(COMMITTED_LOCK, encoding="utf-8") as f:
            committed = f.read()
        assert protocol.dumps(lock_rows) == committed, (
            "protocol.lock.jsonl drifted — the coordination protocol's "
            "collective schedule moved; regenerate deliberately with "
            "`python -m dcgan_tpu.analysis --protocol --write-lock` and "
            "review the diff")

    def test_lock_round_trip(self, lock_rows):
        assert protocol.loads(protocol.dumps(lock_rows)) == \
            sorted(lock_rows, key=protocol._row_key)

    def test_deliberate_drift_is_a_named_finding(self, lock_rows):
        committed = protocol.load_path(COMMITTED_LOCK)
        live = [dict(r) for r in lock_rows]
        row = next(r for r in live if r["kind"] == "scenario")
        row["schedule"] = list(row["schedule"]) + ["ag:extra@9"]
        findings = protocol.lock_diff(live, committed)
        assert any(f.key == "schedule-drift" and "--write-lock"
                   in f.message for f in findings)

    def test_missing_and_uncommitted_rows(self, lock_rows):
        committed = protocol.load_path(COMMITTED_LOCK)
        live = [r for r in lock_rows
                if not (r["kind"] == "scenario"
                        and r["fault"] == "clean")]
        findings = protocol.lock_diff(live, committed)
        assert any(f.key == "missing-row" for f in findings)
        findings = protocol.lock_diff(
            committed + [{"kind": "scenario", "config": "x", "fault": "y",
                          "n_proc": 2, "status": "completed",
                          "outcomes": [], "schedule": []}], committed)
        assert any(f.key == "uncommitted-row" for f in findings)

    def test_missing_lock_file_is_a_finding(self, tmp_path):
        findings, _rows, _stats = protocol.run_protocol(
            lock_path=str(tmp_path / "nope.jsonl"))
        assert any(f.key == "missing-lock" for f in findings)

    def test_drill_replay_ops_from_committed_lock(self):
        assert protocol.drill_replay_ops() == ["stop_consensus"] * 4


# -- DCG013: static divergence lint -------------------------------------------

def _lint(src, path="dcgan_tpu/train/x.py", **cfg):
    sf = core.SourceFile.from_source(src, path)
    return core.run_checks([sf], core.Config(inventory={}, **cfg),
                           checks=["DCG013"])


class TestDivergenceLint:
    def test_wall_clock_branch_into_program_dispatch(self):
        src = ("import time\n"
               "def f(pt, state, z):\n"
               "    t0 = time.monotonic()\n"
               "    while True:\n"
               "        if time.monotonic() - t0 > 30.0:\n"
               "            pt.sample(state, z)\n")
        fs = _lint(src)
        assert [f.check for f in fs] == ["DCG013"]
        assert fs[0].key == "pt.sample"
        assert "host-local" in fs[0].message

    def test_tainted_name_chain(self):
        src = ("import time\n"
               "def f(ckpt, step, state):\n"
               "    t0 = time.time()\n"
               "    waited = t0 - step\n"
               "    if waited > 5:\n"
               "        ckpt.save(step, state)\n")
        fs = _lint(src)
        assert [f.key for f in fs] == ["ckpt.save"]

    def test_process_index_branch(self):
        src = ("import jax\n"
               "def f(ckpt, step, state):\n"
               "    chief = jax.process_index() == 0\n"
               "    if chief:\n"
               "        ckpt.save(step, state)\n")
        assert [f.key for f in _lint(src)] == ["ckpt.save"]

    def test_exception_handler_collective(self):
        src = ("from dcgan_tpu.train.coordination import "
               "anomaly_consensus\n"
               "def f():\n"
               "    try:\n"
               "        risky()\n"
               "    except OSError:\n"
               "        anomaly_consensus(True)\n")
        fs = _lint(src)
        assert [f.key for f in fs] == ["anomaly_consensus"]
        assert "exception handler" in fs[0].message

    def test_handler_counter_branch(self):
        src = ("def f(ckpt, step, state):\n"
               "    fails = 0\n"
               "    try:\n"
               "        risky()\n"
               "    except OSError:\n"
               "        fails += 1\n"
               "    if fails:\n"
               "        ckpt.save(step, state)\n")
        assert [f.key for f in _lint(src)] == ["ckpt.save"]

    def test_consensus_sanitizes_the_branch(self):
        """The blessed shape: gather first, branch on the mesh-uniform
        verdict — the exact structure of the trainer's gate."""
        src = ("from dcgan_tpu.train.coordination import "
               "anomaly_consensus\n"
               "def f(ckpt, step, local_bad):\n"
               "    bad, who = anomaly_consensus(local_bad)\n"
               "    if bad:\n"
               "        ckpt.delete_steps_after(step)\n")
        assert _lint(src) == []

    def test_stop_poll_sanitizes(self):
        src = ("def f(ckpt, step, state, stop):\n"
               "    sig, origins = stop.poll()\n"
               "    if sig is not None:\n"
               "        ckpt.save(step, state)\n")
        assert _lint(src) == []

    def test_argument_position_does_not_taint(self):
        """A function's RESULT is not host-local because an exception
        rode in as an argument (the trainer's rollback.restore(e))."""
        src = ("def f(pt, rollback, state, images, key):\n"
               "    try:\n"
               "        risky()\n"
               "    except FloatingPointError as e:\n"
               "        state, step = rollback.restore(e)\n"
               "    while step < 5:\n"
               "        state, m = pt.step(state, images, key)\n"
               "        step = step + 1\n")
        assert _lint(src) == []

    def test_nested_callback_definition_is_not_a_sink(self):
        """A callback merely DEFINED inside a tainted region runs
        elsewhere (the trainer parks drain lambdas on rollback hooks
        from handler context) — the whole nested def/lambda subtree is
        pruned, not just its root node."""
        src = ("from dcgan_tpu.train.coordination import "
               "warmup_barrier\n"
               "def f(rollback):\n"
               "    try:\n"
               "        risky()\n"
               "    except OSError:\n"
               "        rollback.on_restore = lambda: warmup_barrier()\n"
               "        def _later():\n"
               "            return warmup_barrier()\n"
               "        rollback.late = _later\n")
        assert _lint(src) == []

    def test_sanitizer_reassignment_kills_taint(self):
        """The blessed shape reusing the pre-gather NAME: assignment
        from a consensus call strong-updates the target back to
        mesh-uniform."""
        src = ("import time\n"
               "from dcgan_tpu.train.coordination import "
               "anomaly_consensus, warmup_barrier\n"
               "def f(deadline):\n"
               "    bad = time.monotonic() > deadline\n"
               "    bad, trippers = anomaly_consensus(bad)\n"
               "    if bad:\n"
               "        warmup_barrier()\n")
        assert _lint(src) == []

    def test_plain_reassignment_kills_taint(self):
        src = ("import time\n"
               "def f(pt, state, z):\n"
               "    t = time.monotonic()\n"
               "    t = 0.0\n"
               "    if t > 5:\n"
               "        pt.sample(state, z)\n")
        assert _lint(src) == []

    def test_out_of_scope_module_is_skipped(self):
        src = ("import time\n"
               "def f(pt, state, z):\n"
               "    if time.monotonic() > 5:\n"
               "        pt.sample(state, z)\n")
        assert _lint(src, path="dcgan_tpu/serve/x.py") == []

    def test_suppression_comment(self):
        src = ("import time\n"
               "def f(pt, state, z):\n"
               "    if time.monotonic() > 5:\n"
               "        pt.sample(state, z)  # dcg: disable=DCG013\n")
        assert _lint(src) == []

    def test_routing_error_names_the_protocol_driver(self):
        with pytest.raises(ValueError, match="--protocol"):
            core.run_checks([], core.Config(inventory={}),
                            checks=["DCG012"])


# -- DCG014/015: stale-exemption audits ---------------------------------------

class TestStaleAudits:
    def test_docstring_mention_is_not_a_suppression(self):
        """Suppressions come from real comment tokens only — prose like
        this line must neither suppress nor be audited:
        `# dcg: disable=DCG005` in a docstring is just text."""
        src = ('"""docs say `# dcg: disable=DCG005` here."""\n'
               "x = 1  # dcg: disable=DCG006\n")
        sf = core.SourceFile.from_source(src, "dcgan_tpu/x.py")
        assert list(sf.suppressed) == [2]
        assert sf.suppressed[2] == {"DCG006"}

    def test_dead_suppression_is_flagged(self):
        src = ("import time\n"
               "def f():\n"
               "    return 1  # dcg: disable=DCG005\n")
        sf = core.SourceFile.from_source(src, "dcgan_tpu/x.py")
        suppressed = []
        core.run_checks([sf], core.Config(inventory={}),
                        suppressed_out=suppressed)
        fs = core.audit_stale_suppressions([sf], suppressed)
        assert [(f.check, f.key, f.line) for f in fs] == \
            [("DCG014", "DCG005", 3)]

    def test_working_suppression_is_not_flagged(self):
        src = ("import jax, time\n"
               "def f(x):\n"
               "    k = jax.jit(lambda a: a + time.time())"
               "  # dcg: disable=DCG005\n"
               "    return k(x)\n")
        sf = core.SourceFile.from_source(src, "dcgan_tpu/x.py")
        suppressed = []
        findings = core.run_checks([sf], core.Config(inventory={}),
                                   suppressed_out=suppressed)
        assert not any(f.check == "DCG005" for f in findings)
        assert any(f.check == "DCG005" for f in suppressed)
        assert core.audit_stale_suppressions([sf], suppressed) == []

    def test_stale_baseline_row_scoped_to_ran_checks(self):
        entries = [
            {"check": "DCG006", "path": "p.py", "symbol": "f",
             "key": "open(w)", "why": "x", "_line": 4},
            {"check": "DCG007", "path": "q.py", "symbol": "g",
             "key": "donate", "why": "x", "_line": 5},
        ]
        fs, stale = core.audit_stale_baseline(
            entries, consumed=[], ran_checks=("DCG006",),
            baseline_rel_path="dcgan_tpu/analysis/baseline.jsonl")
        # the DCG007 row's tier did not run — it must NOT be called dead
        assert [f.check for f in fs] == ["DCG015"]
        assert [e["check"] for e in stale] == ["DCG006"]
        assert fs[0].line == 4

    def test_consumed_row_is_not_stale(self):
        f = core.Finding(check="DCG006", path="p.py", line=9, symbol="f",
                         key="open(w)", message="m")
        entries = [{"check": "DCG006", "path": "p.py", "symbol": "f",
                    "key": "open(w)", "why": "x", "_line": 4}]
        fs, stale = core.audit_stale_baseline(
            entries, consumed=[f], ran_checks=("DCG006",),
            baseline_rel_path="b.jsonl")
        assert fs == [] and stale == []

    def test_prune_rewrites_minus_dead_rows(self, tmp_path):
        path = tmp_path / "baseline.jsonl"
        rows = [
            {"check": "DCG006", "path": "p.py", "symbol": "f",
             "key": "a", "why": "keep"},
            {"check": "DCG006", "path": "p.py", "symbol": "g",
             "key": "b", "why": "dead"},
        ]
        path.write_text("# header comment\n"
                        + "\n".join(json.dumps(r) for r in rows) + "\n")
        entries = core.load_baseline(str(path))
        dropped = core.prune_baseline_file(str(path), [entries[1]])
        assert dropped == 1
        text = path.read_text()
        assert text.startswith("# header comment\n")
        assert "keep" in text and "dead" not in text

    def test_path_scoped_run_never_calls_unscanned_rows_dead(
            self, tmp_path):
        """A run over a path subset must neither flag nor prune baseline
        rows anchored on files outside the scan — the committed DCG006
        exemption lives in utils/metrics.py, which a train/-only scan
        never sees."""
        from dcgan_tpu.analysis.__main__ import main

        committed = os.path.join(REPO, "dcgan_tpu", "analysis",
                                 "baseline.jsonl")
        with open(committed, encoding="utf-8") as f:
            original = f.read()
        work = tmp_path / "baseline.jsonl"
        work.write_text(original)
        scoped = os.path.join(REPO, "dcgan_tpu", "train")
        assert main([scoped, "--baseline", str(work)]) == 0
        assert main([scoped, "--baseline", str(work),
                     "--prune-baseline"]) == 0
        assert work.read_text() == original

    def test_lowercase_checks_still_audit_stale_rows(self, tmp_path):
        """--checks IDs are case-normalized everywhere: a lowercase
        `--checks dcg006` must scope the DCG015 audit exactly like the
        uppercase form."""
        from dcgan_tpu.analysis.__main__ import main

        committed = os.path.join(REPO, "dcgan_tpu", "analysis",
                                 "baseline.jsonl")
        with open(committed, encoding="utf-8") as f:
            original = f.read()
        dead = {"check": "DCG006", "path": "dcgan_tpu/gone.py",
                "symbol": "f", "key": "open(w)", "why": "obsolete"}
        work = tmp_path / "baseline.jsonl"
        work.write_text(original + json.dumps(dead) + "\n")
        assert main(["--checks", "dcg006", "--baseline", str(work)]) == 1

    def test_cli_stale_row_fails_then_prunes(self, tmp_path):
        """End-to-end through the AST driver: a dead baseline row is a
        DCG015 exit-1; --prune-baseline resolves it by rewriting the
        file back to the committed content."""
        from dcgan_tpu.analysis.__main__ import main

        committed = os.path.join(REPO, "dcgan_tpu", "analysis",
                                 "baseline.jsonl")
        with open(committed, encoding="utf-8") as f:
            original = f.read()
        work = tmp_path / "baseline.jsonl"
        dead = {"check": "DCG001", "path": "dcgan_tpu/gone.py",
                "symbol": "f", "key": "x->psum", "why": "obsolete"}
        work.write_text(original + json.dumps(dead) + "\n")
        assert main(["--baseline", str(work)]) == 1
        assert main(["--baseline", str(work), "--prune-baseline"]) == 0
        assert work.read_text() == original
        assert main(["--baseline", str(work)]) == 0


# -- driver flag plumbing -----------------------------------------------------

class TestDriverFlags:
    def test_protocol_flags_require_protocol(self, capsys):
        from dcgan_tpu.analysis.__main__ import main

        with pytest.raises(SystemExit):
            main(["--write-lock"])
        assert "--protocol or --all" in capsys.readouterr().err

    def test_all_excludes_per_tier_modes(self, capsys):
        from dcgan_tpu.analysis.__main__ import main

        with pytest.raises(SystemExit):
            main(["--all", "--semantic"])
        assert "mutually exclusive" in capsys.readouterr().err

    def test_protocol_rejects_ast_check_ids(self):
        with pytest.raises(ValueError, match="AST-tier"):
            protocol.run_protocol(checks=["DCG013"])
