"""ResNet GAN family (models/resnet.py, arch="resnet"): the WGAN-GP/SNGAN
residual architecture through the same entry points, machinery, and
parallel layers as the DCGAN stacks."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcgan_tpu.config import MeshConfig, ModelConfig, TrainConfig
from dcgan_tpu.models.dcgan import (
    discriminator_apply,
    gan_init,
    generator_apply,
    sampler_apply,
)

TINY = ModelConfig(arch="resnet", output_size=16, gf_dim=8, df_dim=8,
                   compute_dtype="float32")


def _z(n=4, dim=100, seed=0):
    return jnp.asarray(np.random.default_rng(seed).uniform(
        -1, 1, (n, dim)), jnp.float32)


class TestShapes:
    @pytest.mark.slow
    def test_generator_shapes_and_range(self):
        params, bn = gan_init(jax.random.key(0), TINY)
        img, new_bn = generator_apply(params["gen"], bn["gen"], _z(),
                                      cfg=TINY, train=True)
        assert img.shape == (4, 16, 16, 3)
        assert img.dtype == jnp.float32
        assert float(jnp.abs(img).max()) <= 1.0
        # EMA state advanced for every BN layer
        assert set(new_bn) == set(bn["gen"])

    def test_discriminator_shapes(self):
        params, bn = gan_init(jax.random.key(0), TINY)
        x = _z(4, 16 * 16 * 3).reshape(4, 16, 16, 3)
        prob, logit, _ = discriminator_apply(params["disc"], bn["disc"], x,
                                             cfg=TINY, train=True)
        assert logit.shape == (4, 1) and prob.shape == (4, 1)
        assert logit.dtype == jnp.float32

    def test_critic_is_norm_free(self):
        """SNGAN/WGAN-GP critic carries no BN — its state is empty (or
        sn_* only), so the gradient penalty sees no cross-example
        coupling."""
        params, bn = gan_init(jax.random.key(0), TINY)
        assert bn["disc"] == {}
        sn_cfg = dataclasses.replace(TINY, spectral_norm="d")
        _, sn_bn = gan_init(jax.random.key(0), sn_cfg)
        assert sn_bn["disc"] and all(k.startswith("sn_")
                                     for k in sn_bn["disc"])

    @pytest.mark.slow
    def test_deeper_config_scales(self):
        cfg = dataclasses.replace(TINY, output_size=32)
        params, bn = gan_init(jax.random.key(0), cfg)
        img, _ = generator_apply(params["gen"], bn["gen"], _z(2), cfg=cfg,
                                 train=True)
        assert img.shape == (2, 32, 32, 3)
        # 3 up-blocks: b1..b3; channel halving floors at gf_dim
        assert "b3_conv1" in params["gen"]
        assert params["gen"]["b3_conv1"]["w"].shape[-1] == cfg.gf_dim

    def test_batch_size_not_hardcoded(self):
        params, bn = gan_init(jax.random.key(0), TINY)
        for n in (1, 3, 8):
            img, _ = generator_apply(params["gen"], bn["gen"], _z(n),
                                     cfg=TINY, train=True)
            assert img.shape[0] == n

    def test_sampler_uses_running_stats(self):
        params, bn = gan_init(jax.random.key(0), TINY)
        # advance BN EMA with one train pass, then sample twice — identical
        _, bn_g = generator_apply(params["gen"], bn["gen"], _z(8), cfg=TINY,
                                  train=True)
        a = sampler_apply(params["gen"], bn_g, _z(4, seed=1), cfg=TINY)
        b = sampler_apply(params["gen"], bn_g, _z(4, seed=1), cfg=TINY)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_capture_channels(self):
        params, bn = gan_init(jax.random.key(0), TINY)
        g_cap, d_cap = {}, {}
        img, _ = generator_apply(params["gen"], bn["gen"], _z(), cfg=TINY,
                                 train=True, capture=g_cap)
        discriminator_apply(params["disc"], bn["disc"], img, cfg=TINY,
                            train=True, capture=d_cap)
        assert "h0" in g_cap and "logit" in d_cap


class TestComposition:
    @pytest.mark.slow
    def test_conditional_cbn_attention_sn(self):
        """The whole feature matrix at once: conditional + cBN + attention
        + spectral norm on both nets, one train-mode forward each way."""
        # gf=df=16 so the attention qk projection (ch//8) splits into 2
        # heads at the 8x8 stage
        cfg = dataclasses.replace(TINY, gf_dim=16, df_dim=16,
                                  num_classes=4, conditional_bn=True,
                                  attn_res=8, attn_heads=2,
                                  spectral_norm="gd")
        params, bn = gan_init(jax.random.key(0), cfg)
        labels = jnp.asarray(np.arange(4) % 4)
        img, g_bn = generator_apply(params["gen"], bn["gen"], _z(), cfg=cfg,
                                    train=True, labels=labels)
        assert img.shape == (4, 16, 16, 3)
        assert "attn" in params["gen"]
        assert any(k.startswith("sn_") for k in g_bn)
        # cBN tables are [K, C]
        assert params["gen"]["b1_bn1"]["scale"].ndim == 2
        prob, logit, d_bn = discriminator_apply(
            params["disc"], bn["disc"], img, cfg=cfg, train=True,
            labels=labels)
        assert logit.shape == (4, 1)
        assert any(k.startswith("sn_") for k in d_bn)


@pytest.mark.slow
class TestTraining:
    def test_train_step_and_losses_finite(self):
        from dcgan_tpu.train import make_train_step

        cfg = TrainConfig(model=TINY, batch_size=8)
        fns = make_train_step(cfg)
        state = fns.init(jax.random.key(0))
        xs = jnp.asarray(np.tanh(np.random.default_rng(0).normal(
            size=(8, 16, 16, 3))).astype(np.float32))
        step = jax.jit(fns.train_step, donate_argnums=(0,))
        for i in range(3):
            state, m = step(state, xs, jax.random.fold_in(jax.random.key(1),
                                                          i))
        assert int(state["step"]) == 3
        assert all(np.isfinite(float(v)) for v in m.values())

    def test_wgan_gp_step(self):
        """The family's native loss: norm-free critic + gradient penalty."""
        from dcgan_tpu.train import make_train_step

        cfg = TrainConfig(model=TINY, batch_size=8, loss="wgan-gp",
                          learning_rate=1e-4, beta1=0.0)
        fns = make_train_step(cfg)
        state = fns.init(jax.random.key(0))
        xs = jnp.asarray(np.tanh(np.random.default_rng(0).normal(
            size=(8, 16, 16, 3))).astype(np.float32))
        state, m = jax.jit(fns.train_step)(state, xs, jax.random.key(1))
        assert np.isfinite(float(m["d_loss"]))
        assert np.isfinite(float(m["gp"]))

    def test_sharded_step_matches_single_device(self):
        from dcgan_tpu.parallel import make_parallel_train
        from dcgan_tpu.train import make_train_step

        cfg = TrainConfig(model=TINY, batch_size=16, mesh=MeshConfig())
        xs = jnp.asarray(np.tanh(np.random.default_rng(0).normal(
            size=(16, 16, 16, 3))).astype(np.float32))
        key = jax.random.key(3)

        fns = make_train_step(cfg)
        s_ref, m_ref = jax.jit(fns.train_step)(fns.init(jax.random.key(0)),
                                               xs, key)
        pt = make_parallel_train(cfg)
        s_par, m_par = pt.step(pt.init(jax.random.key(0)), xs, key)
        np.testing.assert_allclose(float(m_par["d_loss"]),
                                   float(m_ref["d_loss"]), rtol=1e-5)
        np.testing.assert_allclose(float(m_par["g_loss"]),
                                   float(m_ref["g_loss"]), rtol=1e-5)

    def test_end_to_end_trainer_and_generate(self, tmp_path):
        """Full loop: train -> config.json carries arch -> zero-flag
        generate reconstructs the resnet family."""
        from dcgan_tpu.generate import build_parser, generate
        from dcgan_tpu.train.trainer import train

        cfg = TrainConfig(
            model=TINY, batch_size=8,
            checkpoint_dir=str(tmp_path / "ckpt"),
            sample_dir=str(tmp_path / "sm"), sample_every_steps=0,
            save_summaries_secs=1e9, save_model_secs=1e9, log_every_steps=0)
        train(cfg, synthetic_data=True, max_steps=2)

        args = build_parser().parse_args(
            ["--checkpoint_dir", cfg.checkpoint_dir,
             "--out_dir", str(tmp_path / "out"), "--num_images", "8",
             "--batch_size", "8", "--grid", "0",
             "--npz", str(tmp_path / "gen.npz")])
        result = generate(args)
        assert result["num_images"] == 8
        imgs = np.load(tmp_path / "gen.npz")["images"]
        assert imgs.shape == (8, 16, 16, 3)
        assert np.isfinite(imgs).all()
